"""Process-wide memory governor: one budget over every byte-holding cache.

Every perf PR since 7 grew a cache — ELL plans, fused-program memos,
mesh shard residency, adapted tablets, plan memos — and none of them
shared a budget or understood bytes. This module is the single registry
they all join: each cache registers a *name* (from the static
`GOVERNED_CACHES` inventory below), a byte-accounting callback, and an
evict-one callback. Two budgets (`device`, `host`) with high/low
watermarks govern them; when resident bytes cross the high watermark the
governor evicts — cheapest-to-rebuild, coldest entry first, ordered by
predicted recompute value per byte (caches derive the value from the
compile/build µs the cost profile already records) — until bytes drop
under the low watermark.

On top of the budgets sits OOM-safe execution. Launch sites wrap their
device dispatch in `oom_retry(site, shape, fn)`: an XLA allocation
failure (`RESOURCE_EXHAUSTED` / `XlaRuntimeError` out-of-memory, or an
injected `AllocFault`) triggers a synchronous evict-to-low-watermark and
ONE retry; a second failure sticky-degrades that (site, shape) to the
caller's host/staged route — bit-identical results, the process never
dies. `set_alloc_fault` is the vault-style process hook the fault
schedule's `alloc` family uses to inject allocation failures at the real
launch sites.

Import discipline: this module must stay importable without jax (facts
extraction and the CLI read `GOVERNED_CACHES` without a device runtime);
jax and flightrec are only touched lazily.
"""

from __future__ import annotations

import weakref

from dgraph_tpu.utils import locks
from dgraph_tpu.utils.metrics import METRICS

__all__ = [
    "GOVERNED_CACHES", "Governor", "GOVERNOR", "AllocFault", "OomDegraded",
    "is_alloc_failure", "set_alloc_fault", "check_alloc_fault", "oom_retry",
    "HIGH_WATERMARK", "LOW_WATERMARK",
]

# ---------------------------------------------------------------------------
# static inventory: every governed cache in the process, by name.
# graftlint R14 pins this both ways — `analysis/facts.py` re-exports it
# verbatim and the runtime registry must register exactly these names —
# so a new byte-holding cache cannot ship ungoverned (the
# cost_record_fields pattern).

GOVERNED_CACHES: dict[str, str] = {
    "fused.program": "whole-query fused programs: compiled XLA callables "
                     "memoized per query shape (PR 15)",
    "batch.plan": "batch plan memo: parsed+grouped plans keyed by query "
                  "shape, shared across identical batches",
    "batch.ell": "host ELL adjacency builds per (snapshot, pred, dir) — "
                 "the padded matrices device kernels consume",
    "batch.ell_dev": "device-resident ELL adjacency (device_put of "
                     "batch.ell entries) — HBM bytes",
    "batch.kernel": "compiled recurse/step kernel callables per static "
                    "launch configuration",
    "store.device": "per-relation CSR (indptr, indices) device arrays "
                    "placed by Store.device_rel",
    "store.sharded": "mesh shard stacks placed by Store.sharded_rel — "
                     "the pod-scale residency (PR 10)",
    "api.tablet": "adapted tablet cache: per-(pred, snapshot) tablets "
                  "the serving path reuses across queries",
    "outofcore.resident": "LazyPreds resident tablets: out-of-core "
                          "postings faulted from disk under its own LRU",
    "timeseries.ring": "retained metrics history: the sampler daemon's "
                       "bounded ring of windowed points (PR 17) — under "
                       "pressure the oldest history is surrendered first",
    "store.vec": "float32vector embedding stacks placed by "
                 "Store.vec_device / vec_sharded — the k-NN seed "
                 "tablets (PR 18); evicted stacks re-place on next use",
}

# watermark fractions of the configured budget: eviction starts above
# HIGH and runs down to LOW (hysteresis so a single fill does not thrash)
HIGH_WATERMARK = 0.90
LOW_WATERMARK = 0.70

_BYTES_BUCKETS = (1 << 20, 16 << 20, 64 << 20, 256 << 20, 1 << 30,
                  4 << 30, 16 << 30)


class AllocFault(RuntimeError):
    """Synthetic allocation failure raised by the injection hook — the
    fault schedule's stand-in for XLA RESOURCE_EXHAUSTED."""


class OomDegraded(RuntimeError):
    """A (site, shape) exhausted its one OOM retry and is now sticky-
    degraded; the caller must serve via its host/staged route."""

    def __init__(self, site: str, shape: str):
        super().__init__(f"oom-degraded: {site} shape={shape}")
        self.site = site
        self.shape = shape


def is_alloc_failure(exc: BaseException) -> bool:
    """Classify an exception as a device allocation failure: the
    injected `AllocFault`, python `MemoryError`, or an XLA runtime
    error whose text carries the canonical out-of-memory markers.
    Matched on type name + message so jax never has to be imported."""
    if isinstance(exc, (AllocFault, MemoryError)):
        return True
    if type(exc).__name__ != "XlaRuntimeError":
        return False
    text = str(exc).lower()
    return ("resource_exhausted" in text or "resource exhausted" in text
            or "out of memory" in text or "allocation failure" in text)


# ---------------------------------------------------------------------------
# allocation-fault injection hook (the vault `set_io_fault` pattern):
# a process-wide callback consulted at every launch site right before
# the device dispatch; returning truthy (or raising) injects the fault.

_alloc_fault_cb = None


def set_alloc_fault(cb) -> None:
    """Install (or clear, with None) the allocation-fault hook. The hook
    receives the launch-site name and injects by returning truthy or
    raising itself; fuzz harnesses arm one-shot closures."""
    global _alloc_fault_cb
    _alloc_fault_cb = cb


def check_alloc_fault(site: str) -> None:
    cb = _alloc_fault_cb
    if cb is not None and cb(site):
        raise AllocFault(f"injected allocation failure at {site}")


class _Entry:
    __slots__ = ("name", "kind", "bytes_cb", "evict_one_cb", "value_cb",
                 "detail_cb", "owner_ref")

    def __init__(self, name, kind, bytes_cb, evict_one_cb, value_cb,
                 owner, detail_cb=None):
        self.name = name
        self.kind = kind
        self.bytes_cb = bytes_cb
        self.evict_one_cb = evict_one_cb
        self.value_cb = value_cb
        self.detail_cb = detail_cb
        self.owner_ref = weakref.ref(owner) if owner is not None else None

    def alive(self) -> bool:
        return self.owner_ref is None or self.owner_ref() is not None

    def bytes(self) -> int:
        try:
            return int(self.bytes_cb())
        except Exception:
            return 0

    def value(self) -> float:
        """Predicted recompute µs per byte of the entry this cache would
        evict next — lower is cheaper to rebuild, so evicted first; a
        cache with no opinion (None) evicts before any priced one."""
        if self.value_cb is None:
            return 0.0
        try:
            v = self.value_cb()
        except Exception:
            return 0.0
        return 0.0 if v is None else float(v)

    def detail(self) -> list:
        """Per-resident rows for /debug/memory (e.g. a vec cache's
        placed stacks with their dims); [] when the cache has no
        detail callback or it fails."""
        if self.detail_cb is None:
            return []
        try:
            return list(self.detail_cb())
        except Exception:
            return []


class Governor:
    """The process-wide cache registry + budget enforcer. Callbacks are
    always invoked OUTSIDE the governor lock (entries are snapshotted
    under it first) so cache-internal locks never order against ours."""

    def __init__(self):
        self._lock = locks.make_lock("memgov.governor")
        locks.guarded(self, "memgov.governor")
        self._entries: dict[int, _Entry] = {}
        self._next_id = 0
        self._budgets = {"device": 0, "host": 0}
        self._armed = False          # any budget set (lock-free fast path)
        self._evictions: dict[str, int] = {}
        self._oom_events = 0
        self._oom_retries = 0
        self._degraded: dict[tuple[str, str], int] = {}
        self._deg_lock = locks.make_lock("memgov.degraded")  # leaf lock

    # -- registration -----------------------------------------------------

    def register(self, name: str, kind: str, bytes_cb, evict_one_cb,
                 value_cb=None, owner=None, detail_cb=None) -> int:
        """Join the registry. `name` must appear in GOVERNED_CACHES and
        `kind` is the budget it draws from ("device" | "host").
        `bytes_cb()` returns resident bytes; `evict_one_cb()` drops the
        cache's coldest entry and returns bytes freed (0 when empty);
        `value_cb()` prices that coldest entry in recompute-µs-per-byte.
        Per-instance caches pass `owner` so dead instances fall out of
        the registry via weakref."""
        if name not in GOVERNED_CACHES:
            raise ValueError(f"unknown governed cache {name!r} — add it "
                             f"to memgov.GOVERNED_CACHES")
        if kind not in ("device", "host"):
            raise ValueError(f"bad cache kind {kind!r}")
        e = _Entry(name, kind, bytes_cb, evict_one_cb, value_cb, owner,
                   detail_cb)
        with self._lock:
            self._next_id += 1
            rid = self._next_id
            self._entries[rid] = e
            self._prune_locked()
        return rid

    def unregister(self, rid: int) -> None:
        with self._lock:
            self._entries.pop(rid, None)

    def _prune_locked(self) -> None:
        dead = [k for k, e in self._entries.items() if not e.alive()]
        for k in dead:
            del self._entries[k]

    def registered_names(self) -> set:
        with self._lock:
            return {e.name for e in self._entries.values() if e.alive()}

    def _snapshot(self, kind=None) -> list:
        with self._lock:
            self._prune_locked()
            return [e for e in self._entries.values()
                    if e.alive() and (kind is None or e.kind == kind)]

    # -- budgets / accounting ---------------------------------------------

    def set_budgets(self, device_bytes: int = 0,
                    host_bytes: int = 0) -> None:
        """Configure the budgets (0 disarms a kind). Watermarks are
        fractions of the budget: evict above HIGH, down to LOW."""
        with self._lock:
            self._budgets["device"] = int(device_bytes)
            self._budgets["host"] = int(host_bytes)
        self._armed = bool(device_bytes or host_bytes)

    def budget(self, kind: str) -> int:
        return self._budgets[kind]

    def resident_bytes(self, kind: str) -> int:
        return sum(e.bytes() for e in self._snapshot(kind))

    # -- eviction ---------------------------------------------------------

    def maybe_evict(self, kind: str) -> int:
        """Cache fill hook: when the kind's budget is armed and resident
        bytes crossed the high watermark, evict down to the low one.
        Unarmed processes pay one attribute read (the hot-path bound the
        <5% overhead guard pins)."""
        if not self._armed:
            return 0
        budget = self._budgets[kind]
        if not budget:
            return 0
        if self.resident_bytes(kind) <= int(budget * HIGH_WATERMARK):
            return 0
        return self.evict_to_low(kind)

    def evict_to_low(self, kind: str) -> int:
        """Synchronous eviction pass: drop entries — lowest recompute-
        value-per-byte across caches first, each cache surrendering its
        own coldest entry — until resident bytes fall under the low
        watermark (or nothing evictable remains). Returns bytes freed."""
        budget = self._budgets[kind]
        low = int(budget * LOW_WATERMARK) if budget else 0
        freed = 0
        while self.resident_bytes(kind) > low:
            candidates = [e for e in self._snapshot(kind) if e.bytes() > 0]
            if not candidates:
                break
            candidates.sort(key=lambda e: e.value())
            got = 0
            for e in candidates:
                got = int(e.evict_one_cb() or 0)
                if got > 0:
                    METRICS.inc("cache_evictions_total", cache=e.name)
                    with self._lock:
                        self._evictions[e.name] = (
                            self._evictions.get(e.name, 0) + 1)
                    freed += got
                    break
            if got <= 0:      # every candidate refused: no progress
                break
        return freed

    # -- pressure (admission integration) ---------------------------------

    def admission_pressure(self):
        """Sustained-pressure probe for admission: a kind still above its
        high watermark AFTER an eviction pass (nothing left to shed but
        load). Returns the kind name, or None. Unarmed: one attribute
        read."""
        if not self._armed:
            return None
        for kind in ("device", "host"):
            budget = self._budgets[kind]
            if not budget:
                continue
            high = int(budget * HIGH_WATERMARK)
            if self.resident_bytes(kind) > high:
                self.evict_to_low(kind)
                if self.resident_bytes(kind) > high:
                    return kind
        return None

    # -- OOM lifecycle ----------------------------------------------------

    def note_oom(self, site: str, shape: str, kind: str = "device") -> int:
        """One allocation failure observed at a launch site: count it,
        flight-record it, and synchronously evict the kind to its low
        watermark so the retry has room. Returns bytes freed."""
        with self._deg_lock:
            self._oom_events += 1
            self._oom_retries += 1
        METRICS.inc("oom_events_total", site=site)
        freed = self.evict_to_low(kind)
        try:
            from dgraph_tpu.utils import flightrec
            flightrec.emit("memory.oom", site=site, shape=str(shape),
                           freed_bytes=freed)
        except Exception:
            pass
        return freed

    def degrade(self, site: str, shape: str) -> None:
        """Sticky-degrade a (site, shape): its one retry also failed, so
        every future request on the shape takes the host/staged route
        until reset. Bit-identical results, no process death."""
        with self._deg_lock:
            key = (site, str(shape))
            self._degraded[key] = self._degraded.get(key, 0) + 1
            n = len(self._degraded)
        METRICS.set_gauge("oom_degraded", float(n))
        try:
            from dgraph_tpu.utils import flightrec
            flightrec.emit("memory.degrade", site=site, shape=str(shape))
        except Exception:
            pass

    def is_degraded(self, site: str, shape) -> bool:
        with self._deg_lock:
            return (site, str(shape)) in self._degraded

    def oom_stats(self) -> dict:
        """Counters the watchdog's kind=oom scan convicts on."""
        with self._deg_lock:
            return {"events": self._oom_events,
                    "retries": self._oom_retries,
                    "degraded": len(self._degraded)}

    # -- introspection ----------------------------------------------------

    def status(self) -> dict:
        """The /debug/memory document: budgets + watermarks, per-cache
        resident bytes and evictions, OOM lifecycle state."""
        caches: dict[str, dict] = {}
        for e in self._snapshot():
            b = e.bytes()
            c = caches.setdefault(e.name, {"kind": e.kind, "bytes": 0,
                                           "registrants": 0})
            c["bytes"] += b
            c["registrants"] += 1
            d = e.detail()
            if d:
                c.setdefault("detail", []).extend(d)
        with self._lock:
            ev = dict(self._evictions)
            budgets = dict(self._budgets)
        for name, c in caches.items():
            c["evictions"] = ev.get(name, 0)
            METRICS.set_gauge("cache_resident_bytes", float(c["bytes"]),
                              cache=name)
        kinds = {}
        for kind in ("device", "host"):
            budget = budgets[kind]
            kinds[kind] = {
                "budget_bytes": budget,
                "high_bytes": int(budget * HIGH_WATERMARK),
                "low_bytes": int(budget * LOW_WATERMARK),
                "resident_bytes": sum(c["bytes"] for c in caches.values()
                                      if c["kind"] == kind),
            }
        with self._deg_lock:
            degraded = [{"site": s, "shape": sh, "count": n}
                        for (s, sh), n in sorted(self._degraded.items())]
            oom = {"events": self._oom_events,
                   "retries": self._oom_retries}
        # read-only pressure: above-high without triggering an eviction
        pressure = None
        for kind in ("device", "host"):
            k = kinds[kind]
            if k["budget_bytes"] and k["resident_bytes"] > k["high_bytes"]:
                pressure = kind
                break
        return {"budgets": kinds, "caches": caches,
                "oom": oom, "degraded": degraded,
                "pressure": pressure}

    def reset(self, full: bool = False) -> None:
        """Test hook: clear budgets, eviction/OOM counters and sticky
        degrades (registrations survive unless full=True — module-level
        memos register once at import)."""
        with self._lock:
            self._budgets = {"device": 0, "host": 0}
            self._evictions.clear()
            if full:
                self._entries.clear()
        self._armed = False
        with self._deg_lock:
            self._oom_events = 0
            self._oom_retries = 0
            self._degraded.clear()
        METRICS.set_gauge("oom_degraded", 0.0)


GOVERNOR = Governor()


def oom_retry(site: str, shape, fn, kind: str = "device"):
    """Run one device launch with the OOM lifecycle: an allocation
    failure triggers evict-to-low-watermark and ONE retry; a second
    failure sticky-degrades the (site, shape) and raises `OomDegraded`
    for the caller's host/staged fallback. A shape already degraded
    raises immediately (the sticky fast path). Any non-allocation
    exception passes through untouched."""
    if GOVERNOR.is_degraded(site, shape):
        raise OomDegraded(site, str(shape))
    try:
        check_alloc_fault(site)
        return fn()
    except Exception as e:
        if not is_alloc_failure(e):
            raise
        GOVERNOR.note_oom(site, str(shape), kind=kind)
        try:
            check_alloc_fault(site)
            return fn()
        except Exception as e2:
            if not is_alloc_failure(e2):
                raise
            GOVERNOR.degrade(site, str(shape))
            raise OomDegraded(site, str(shape)) from e2


def estimate_nbytes(value) -> int:
    """Best-effort byte size of a cached value: arrays report .nbytes,
    containers sum their members, everything else costs sys.getsizeof.
    An estimator, not an audit — budgets only need relative truth."""
    import sys
    seen_bytes = 0
    stack = [value]
    depth = 0
    while stack and depth < 4096:
        depth += 1
        v = stack.pop()
        nb = getattr(v, "nbytes", None)
        if nb is not None:
            try:
                seen_bytes += int(nb)
                continue
            except Exception:
                pass
        if isinstance(v, (tuple, list)):
            stack.extend(v)
        elif isinstance(v, dict):
            stack.extend(v.values())
        elif hasattr(v, "__dataclass_fields__"):
            stack.extend(vars(v).values())   # EllGraph/DeviceEll et al.
        else:
            seen_bytes += sys.getsizeof(v, 64)
    return seen_bytes
