"""Prometheus-text metrics registry with labels.

Reference parity: `x/metrics.go` + the `/debug/prometheus_metrics`
endpoint — query latency histograms, pending txns, and (our north-star
first-class counter, per BASELINE.json) edges traversed. No client
library dependency: counters/gauges/histograms rendered in Prometheus
text exposition format directly, including label sets with the escaping
rules the format mandates (`\\`, `\"`, `\n` in label values).

Every series is keyed (name, sorted label tuple); label-free calls keep
their historical plain-name identity so existing consumers (snapshot
readers, the cluster transfer-byte tests) see no change. Histograms use
the standard µs latency bucket ladder (`BUCKETS_US`) unless the first
observation for a name registers a custom ladder.

Cardinality guard: a label value sourced from data (predicate names,
peer addrs) can explode a metric into unbounded series — the classic
Prometheus cardinality bomb. Each metric NAME admits at most
`max_label_sets` distinct label-value sets (default MAX_LABEL_SETS;
`set_label_limit` overrides per name); later novel sets collapse into
one overflow series labeled `other="true"`, and every collapsed
recording counts in `metrics_series_dropped_total` so the clamp itself
is visible. Known sets keep recording exactly — only NEW identities
overflow.
"""

from __future__ import annotations

from dgraph_tpu.utils import locks

# standard µs latency ladder: 100µs … 10s, then +Inf
BUCKETS_US = (100, 1_000, 10_000, 100_000, 1_000_000, 10_000_000)
_BUCKETS = BUCKETS_US  # back-compat alias

MAX_LABEL_SETS = 64              # default per-name label-set cap
OVERFLOW_KEY = (("other", "true"),)  # where novel sets collapse
DROPPED_SERIES = "metrics_series_dropped_total"


def _label_key(labels: dict) -> tuple:
    # values stringify at the key: one series per rendered identity, and
    # render()'s sorted() never compares int with str across series
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _escape(v) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _series(name: str, lk: tuple, extra: str = "") -> str:
    """`name` or `name{a="b",...}`; `extra` appends e.g. the le label."""
    parts = [f'{k}="{_escape(v)}"' for k, v in lk]
    if extra:
        parts.append(extra)
    return f"{name}{{{','.join(parts)}}}" if parts else name


class Registry:
    def __init__(self):
        self._lock = locks.make_lock("metrics.registry")
        self._counters: dict[tuple[str, tuple], float] = {}
        self._gauges: dict[tuple[str, tuple], float] = {}
        self._hists: dict[tuple[str, tuple], list] = {}
        self._hist_buckets: dict[str, tuple] = {}
        self._label_sets: dict[str, set] = {}   # name → admitted label sets
        self._label_limits: dict[str, int] = {}  # per-name cap overrides
        self.max_label_sets = MAX_LABEL_SETS
        self._enabled = True
        locks.guarded(self, "metrics.registry")

    def set_enabled(self, flag: bool) -> None:
        """Disarm recording (render/snapshot still serve what exists) —
        the switch the <5% query-path overhead guard flips."""
        self._enabled = bool(flag)

    def set_label_limit(self, name: str, n: int) -> None:
        """Per-name override of the label-set cardinality cap."""
        with self._lock:
            self._label_limits[name] = int(n)

    def _guard(self, name: str, lk: tuple) -> tuple:
        """Admit or collapse a label set (caller holds the lock).
        Label-free series and already-admitted sets pass through; a
        novel set past the cap collapses to `other="true"` and counts
        a dropped recording."""
        if not lk or lk == OVERFLOW_KEY:
            return lk
        seen = self._label_sets.setdefault(name, set())
        if lk in seen:
            return lk
        cap = self._label_limits.get(name, self.max_label_sets)
        if len(seen) >= cap:
            dk = (DROPPED_SERIES, ())
            self._counters[dk] = self._counters.get(dk, 0.0) + 1.0
            return OVERFLOW_KEY
        seen.add(lk)
        return lk

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        if not self._enabled:
            return
        lk = _label_key(labels)
        with self._lock:
            k = (name, self._guard(name, lk))
            self._counters[k] = self._counters.get(k, 0.0) + value

    def set_gauge(self, name: str, value: float, **labels) -> None:
        if not self._enabled:
            return
        lk = _label_key(labels)
        with self._lock:
            self._gauges[(name, self._guard(name, lk))] = value

    def observe(self, name: str, value: float,
                buckets: tuple | None = None, **labels) -> None:
        """Histogram observation. Buckets default to the µs ladder; a
        custom ladder binds to `name` on first observation (per-name, so
        every label set of one histogram shares one ladder)."""
        if not self._enabled:
            return
        with self._lock:
            k = (name, self._guard(name, _label_key(labels)))
            bks = self._hist_buckets.setdefault(
                name, tuple(buckets) if buckets else BUCKETS_US)
            h = self._hists.get(k)
            if h is None:
                h = self._hists[k] = [[0] * (len(bks) + 1), 0.0, 0]
            counts, _sum, _n = h
            for i, b in enumerate(bks):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            h[1] += value
            h[2] += 1

    def get(self, name: str, **labels) -> float:
        """Current counter value (0.0 when the series doesn't exist)."""
        with self._lock:
            return self._counters.get((name, _label_key(labels)), 0.0)

    def render(self) -> str:
        """Prometheus text exposition format."""
        out = []
        with self._lock:
            for kind, table in (("counter", self._counters),
                                ("gauge", self._gauges)):
                last_name = None
                for (name, lk), v in sorted(table.items()):
                    if name != last_name:
                        out.append(f"# TYPE dgraph_tpu_{name} {kind}")
                        last_name = name
                    out.append(f"dgraph_tpu_{_series(name, lk)} {v}")
            last_name = None
            for (name, lk), (counts, s, n) in sorted(self._hists.items()):
                if name != last_name:
                    out.append(f"# TYPE dgraph_tpu_{name} histogram")
                    last_name = name
                bks = self._hist_buckets[name]
                acc = 0
                for b, c in zip(bks, counts):
                    acc += c
                    le = f'le="{b}"'
                    out.append(
                        f"dgraph_tpu_{_series(name + '_bucket', lk, le)}"
                        f" {acc}")
                inf = 'le="+Inf"'
                out.append(
                    f"dgraph_tpu_{_series(name + '_bucket', lk, inf)} {n}")
                out.append(f"dgraph_tpu_{_series(name + '_sum', lk)} {s}")
                out.append(f"dgraph_tpu_{_series(name + '_count', lk)} {n}")
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        """Flat dict view. Label-free series keep their bare name (the
        historical shape); labeled ones render as `name{k="v",...}`."""
        with self._lock:
            return {
                "counters": {_series(n, lk): v
                             for (n, lk), v in self._counters.items()},
                "gauges": {_series(n, lk): v
                           for (n, lk), v in self._gauges.items()},
            }

    def hist_snapshot(self) -> dict:
        """Histogram series view for the time-series sampler: rendered
        series name → {"buckets": ladder, "counts": cumulative-free
        per-bucket counts (last slot = +Inf), "sum": Σvalues, "n": N}.
        Copies under the lock so the sampler diffs stable points."""
        with self._lock:
            return {
                _series(n, lk): {"buckets": self._hist_buckets[n],
                                 "counts": list(counts),
                                 "sum": s, "n": n_obs}
                for (n, lk), (counts, s, n_obs) in self._hists.items()
            }


METRICS = Registry()
