"""Prometheus-text metrics registry.

Reference parity: `x/metrics.go` + the `/debug/prometheus_metrics`
endpoint — query latency histograms, pending txns, and (our north-star
first-class counter, per BASELINE.json) edges traversed. No client
library dependency: counters/gauges/histograms rendered in Prometheus
text exposition format directly.
"""

from __future__ import annotations

import threading
from collections import defaultdict

_BUCKETS = (100, 1_000, 10_000, 100_000, 1_000_000, 10_000_000)


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, list] = {}

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        """Histogram observation (µs-scale buckets)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = [[0] * (len(_BUCKETS) + 1), 0.0, 0]
            counts, _sum, _n = h
            for i, b in enumerate(_BUCKETS):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            h[1] += value
            h[2] += 1

    def render(self) -> str:
        """Prometheus text exposition format."""
        out = []
        with self._lock:
            for k, v in sorted(self._counters.items()):
                out.append(f"# TYPE dgraph_tpu_{k} counter")
                out.append(f"dgraph_tpu_{k} {v}")
            for k, v in sorted(self._gauges.items()):
                out.append(f"# TYPE dgraph_tpu_{k} gauge")
                out.append(f"dgraph_tpu_{k} {v}")
            for k, (counts, s, n) in sorted(self._hists.items()):
                out.append(f"# TYPE dgraph_tpu_{k} histogram")
                acc = 0
                for b, c in zip(_BUCKETS, counts):
                    acc += c
                    out.append(
                        f'dgraph_tpu_{k}_bucket{{le="{b}"}} {acc}')
                out.append(
                    f'dgraph_tpu_{k}_bucket{{le="+Inf"}} {n}')
                out.append(f"dgraph_tpu_{k}_sum {s}")
                out.append(f"dgraph_tpu_{k}_count {n}")
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        with self._lock:
            return {"counters": dict(self._counters),
                    "gauges": dict(self._gauges)}


METRICS = Registry()
