"""Query cost profiles: shape-keyed resource accounting.

ROADMAP's cost-model item (the TpuGraphs direction, PAPERS) needs a
DATASET: per-query records joining the plan features that predict cost
(query-shape fingerprint, lane count, padding, depth, cache-hit bits,
tablet sizes) with the measured costs the observability layer already
produces (admission wait, parse/plan/build, per-kernel-family compile vs
execute, bytes gathered, edges traversed, RPC legs/retries/failovers,
outcome). PR 6's facts inventory catalogs the STATIC half (every
launchable kernel with its retrace axes); this module is the RUNTIME
half — the two share one field vocabulary (`FIELDS`, re-exported by
analysis/facts.py and pinned in sync by tests/test_lint.py) so a
recorded cost joins back to the kernel that incurred it.

Collection is ambient, like utils/deadline.py: `Alpha._request` opens a
thread-local `Recorder` via `profile(lane)`; contributor sites
(admission, the batch planner, jit_call, engine expansion, cluster RPC
legs) call the module-level `note/add/add_shape/add_kernel`, which are
one thread-local load + None check when no recorder is active — the
same <5% uncontended-overhead bar tracing holds (tier-1 guard in
tests/test_costprofile.py).

Aggregation: finished records fold into `COSTS`, shape-keyed
percentile DIGESTS (power-of-two bucket histograms: integer state, so
merge is exact and associative — bench and serving records combine).
Shape cardinality is bounded the way utils/metrics.py bounds label
sets: at most `max_shapes` distinct shapes (default MAX_LABEL_SETS),
later novel shapes collapse into `other` and count
`cost_shapes_dropped_total`. The aggregate persists as JSON next to
the checkpoint dir (`costprofiles.json`) and merges across restarts.

Surfaces: `GET /debug/costs` (per-shape digests + top-N most expensive
shapes), a `query.cost` span per request (the record's trace/span
attribute form), `recent()` for the live push pipeline
(utils/push.py), and a `cost_records` summary in BENCH JSON.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time

from dgraph_tpu.utils import deadline as dl
from dgraph_tpu.utils import locks
from dgraph_tpu.utils.metrics import MAX_LABEL_SETS, METRICS

__all__ = ["FIELDS", "DIGEST_FIELDS", "FEATURE_FIELDS", "Digest",
           "Recorder", "Aggregator", "COSTS", "profile", "active",
           "note", "note_max", "add", "add_shape", "add_kernel",
           "note_launch", "launch_frame",
           "add_tablet_cost", "tablet_costs",
           "add_shard_cost", "shard_costs", "recent",
           "add_sink", "remove_sink", "set_enabled", "summary",
           "save", "load", "reset"]

# -- the cost-record schema ---------------------------------------------------
# One vocabulary for the runtime records AND the static facts inventory
# (analysis/facts.py re-exports this; tests/test_lint.py pins the sync).
# kind "cost" fields aggregate into per-shape percentile digests; kind
# "feature" fields aggregate as per-shape means (the cost model's
# regressors); kind "meta" fields identify/classify the record.
FIELDS: dict[str, dict] = {
    # meta
    "shape":             {"kind": "meta", "doc": "query-shape fingerprint (the digest key)"},
    "trace_id":          {"kind": "meta", "doc": "trace id — joins the record to its span tree"},
    "lane":              {"kind": "meta", "doc": "admission lane (read/mutate)"},
    "outcome":           {"kind": "meta", "doc": "ok | shed | deadline | cancelled | error"},
    "kernels":           {"kind": "meta", "doc": "per-kernel-family {compile_us, execute_us} breakdown"},
    # measured costs (digested per shape)
    "total_us":          {"kind": "cost", "doc": "whole-request wall µs inside Alpha._request"},
    "admission_wait_us": {"kind": "cost", "doc": "time queued before admission (admission.wait span)"},
    "plan_us":           {"kind": "cost", "doc": "parse + batch planning µs (batch.plan span)"},
    "build_us":          {"kind": "cost", "doc": "ELL/index build µs (batch.build_ell span)"},
    "compile_us":        {"kind": "cost", "doc": "jit compile µs across kernel families (jit.compile)"},
    "execute_us":        {"kind": "cost", "doc": "kernel execute µs across families (batch.*_kernel)"},
    "bytes_gathered":    {"kind": "cost", "doc": "bytes moved by expansions/kernel gathers (model)"},
    "edges_traversed":   {"kind": "cost", "doc": "edges the request traversed (the north-star count)"},
    "rpc_legs":          {"kind": "cost", "doc": "outbound cluster RPC attempts"},
    "rpc_retries":       {"kind": "cost", "doc": "re-attempts the resilience layer spent"},
    "rpc_failovers":     {"kind": "cost", "doc": "read legs served by a non-preferred replica"},
    "predicted_us":      {"kind": "cost", "doc": "scheduler's pre-run cost prediction (utils/costprior.py; 0 = no prediction)"},
    # plan features (averaged per shape)
    "lanes":             {"kind": "feature", "doc": "kernel lanes launched (padded batch width)"},
    "padded_lanes":      {"kind": "feature", "doc": "zero-seeded padding lanes"},
    "padding_frac":      {"kind": "feature", "doc": "padded_lanes / lanes (scaled x1000)"},
    "depth":             {"kind": "feature", "doc": "kernel recursion depth (static compile axis)"},
    "bucket_mix":        {"kind": "feature", "doc": "segment-CSR degree-bucket blocks in the launched ELL"},
    "queries":           {"kind": "feature", "doc": "queries in the request (batch size)"},
    "tablet_rows":       {"kind": "feature", "doc": "rows of the largest tablet touched"},
    "plan_cache_hit":    {"kind": "feature", "doc": "1 = batch plan memo hit"},
    "ell_cache_hit":     {"kind": "feature", "doc": "1 = every ELL build was a snapshot-cache hit"},
    "jit_cache_hits":    {"kind": "feature", "doc": "jit compile-cache hits during the request"},
    "mesh_shards":       {"kind": "feature", "doc": "mesh shards engaged by the request's expansions (0 = no mesh route)"},
    "kernel_launches":   {"kind": "feature", "doc": "separately dispatched device kernel launches (the count whole-query fusion collapses to 1)"},
    "launch_gap_us":     {"kind": "feature", "doc": "host-side µs between consecutive kernel launches — the dispatch overhead baseline for the fusion item"},
}

DIGEST_FIELDS = tuple(n for n, d in FIELDS.items() if d["kind"] == "cost")
FEATURE_FIELDS = tuple(n for n, d in FIELDS.items()
                       if d["kind"] == "feature")

_N_BUCKETS = 42          # power-of-two ladder: 1, 2, 4, … 2^40, +overflow
_RECENT_MAX = 512        # records retained for /debug/costs + push
UNCLASSIFIED = "unclassified"
OVERFLOW_SHAPE = "other"  # where novel shapes past the cap collapse


class Digest:
    """Bounded mergeable percentile digest over non-negative values.

    Power-of-two buckets with INTEGER state (counts, sum, min, max):
    merging is elementwise integer addition, hence exact and associative
    — the property that lets bench records, serving records, and
    restart-persisted records combine in any order (pinned by
    tests/test_costprofile.py). Bucket index is `int(v).bit_length()`,
    so adding costs no search; percentiles interpolate at the bucket
    midpoint and clamp into the exact [min, max] envelope."""

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self):
        self.counts = [0] * _N_BUCKETS
        self.count = 0
        self.sum = 0
        self.min = None
        self.max = 0

    def add(self, value) -> None:
        v = int(value)
        if v < 0:
            v = 0
        i = min(v.bit_length(), _N_BUCKETS - 1)
        self.counts[i] += 1
        self.count += 1
        self.sum += v
        self.max = max(self.max, v)
        self.min = v if self.min is None else min(self.min, v)

    def merge(self, other: "Digest") -> "Digest":
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        self.max = max(self.max, other.max)
        if other.min is not None:
            self.min = (other.min if self.min is None
                        else min(self.min, other.min))
        return self

    def percentile(self, p: float) -> int:
        """Approximate p-quantile (p in [0,1]): the midpoint of the
        bucket holding the p-th observation, clamped to [min, max]."""
        if not self.count:
            return 0
        rank = max(1, int(p * self.count + 0.999999))
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= rank:
                # bucket i holds [2^(i-1), 2^i); report its midpoint
                mid = ((1 << (i - 1)) + (1 << i)) // 2 if i else 0
                lo = self.min or 0
                return max(lo, min(mid, self.max))
        return self.max

    def to_dict(self) -> dict:
        return {"counts": list(self.counts), "count": self.count,
                "sum": self.sum, "min": self.min, "max": self.max}

    @classmethod
    def from_dict(cls, d: dict) -> "Digest":
        g = cls()
        src = list(d.get("counts", ()))[:_N_BUCKETS]
        for i, c in enumerate(src):
            g.counts[i] = int(c)
        g.count = int(d.get("count", 0))
        g.sum = int(d.get("sum", 0))
        g.min = d.get("min")
        if g.min is not None:
            g.min = int(g.min)
        g.max = int(d.get("max", 0))
        return g


class Recorder:
    """One request's accumulation buffer. Not thread-safe by design:
    it is thread-local for its request thread; cross-thread
    contributors (none today) would need their own record."""

    __slots__ = ("lane", "vals", "shapes", "kernels", "t0", "trace_id",
                 "_last_launch_end")

    def __init__(self, lane: str):
        self.lane = lane
        self.vals: dict[str, float] = {}
        self.shapes: list[str] = []
        self.kernels: dict[str, dict] = {}
        self.t0 = time.perf_counter()
        self._last_launch_end: float | None = None
        from dgraph_tpu.utils import tracing
        self.trace_id = tracing.current_trace_id()

    def note(self, field: str, value) -> None:
        self.vals[field] = value

    def add(self, field: str, delta) -> None:
        self.vals[field] = self.vals.get(field, 0) + delta

    def add_shape(self, component: str) -> None:
        if component not in self.shapes:
            self.shapes.append(component)

    def note_max(self, field: str, value) -> None:
        if value > self.vals.get(field, 0):
            self.vals[field] = value

    def shape_key(self) -> str:
        """The digest key this record will fold under — exposed so the
        scheduler (utils/costprior.py) can map query text → shape while
        the request is still open (finish() uses the same rule)."""
        return ("+".join(sorted(self.shapes))
                or self.lane or UNCLASSIFIED)

    def note_launch(self, start_t: float, end_t: float) -> None:
        """One device kernel launch spanning [start_t, end_t) on the
        host's perf_counter clock. Counts launches and accumulates the
        HOST-SIDE GAP since the previous launch ended — the per-request
        launch/dispatch overhead the whole-query-fusion item needed a
        measured baseline for (per-shape means surface at /debug/costs,
        and the fused path's acceptance number is this feature
        collapsing to 1). The last-launch timestamp is per-Recorder-
        FRAME (`launch_frame`): a nested sub-request leg (an upsert's
        query, a txn read inside a mutate) interleaving launches on the
        same thread must not bill its leg boundary — which includes
        parse/apply work, not dispatch overhead — as a launch gap."""
        self.add("kernel_launches", 1)
        last = self._last_launch_end
        if last is not None and start_t > last:
            self.add("launch_gap_us", int((start_t - last) * 1e6))
        self._last_launch_end = end_t

    @contextlib.contextmanager
    def launch_frame(self):
        """Scope one nested sub-request leg's launch-gap accounting:
        entering resets the gap baseline (the outer leg's last launch
        is not this leg's predecessor), leaving resets it again (this
        leg's last launch is not the outer leg's predecessor). Launch
        COUNTS still accumulate into the one shared record — only the
        gap attribution is frame-local."""
        self._last_launch_end = None
        try:
            yield
        finally:
            self._last_launch_end = None

    def add_kernel(self, family: str, compile_us: float = 0.0,
                   execute_us: float = 0.0) -> None:
        k = self.kernels.setdefault(family,
                                    {"compile_us": 0, "execute_us": 0})
        k["compile_us"] += int(compile_us)
        k["execute_us"] += int(execute_us)
        if compile_us:
            self.add("compile_us", int(compile_us))
        if execute_us:
            self.add("execute_us", int(execute_us))

    def finish(self, outcome: str) -> dict:
        # no shape component (mutations, schema queries): the lane is
        # the coarsest honest shape — never a silent "unclassified"
        # unless even the lane is unknown
        rec = {"shape": self.shape_key(),
               "trace_id": self.trace_id, "lane": self.lane,
               "outcome": outcome,
               "total_us": int((time.perf_counter() - self.t0) * 1e6),
               "kernels": self.kernels}
        for f in DIGEST_FIELDS:
            if f != "total_us":
                rec[f] = int(self.vals.get(f, 0))
        for f in FEATURE_FIELDS:
            rec[f] = int(self.vals.get(f, 0))
        return rec


class _ShapeStats:
    __slots__ = ("count", "digests", "features")

    def __init__(self):
        self.count = 0
        self.digests = {f: Digest() for f in DIGEST_FIELDS}
        self.features = dict.fromkeys(FEATURE_FIELDS, 0)

    def record(self, rec: dict) -> None:
        self.count += 1
        for f in DIGEST_FIELDS:
            self.digests[f].add(rec.get(f, 0))
        for f in FEATURE_FIELDS:
            self.features[f] += int(rec.get(f, 0))


class Aggregator:
    """Shape-keyed digest store: bounded cardinality, exact merge,
    JSON persistence. The module-level `COSTS` instance is the
    process-wide registry (METRICS-style); tests construct their own."""

    def __init__(self, max_shapes: int = MAX_LABEL_SETS):
        self._lock = locks.make_lock("costprofile.aggregator")
        self._shapes: dict[str, _ShapeStats] = {}
        self.max_shapes = int(max_shapes)
        self.records_total = 0
        locks.guarded(self, "costprofile.aggregator")

    def _guard(self, shape: str) -> str:
        """Admit or collapse a shape key (caller holds the lock) — the
        metrics label-limit discipline applied to shapes: known keys
        keep recording exactly, novel keys past the cap collapse into
        `other` and count the clamp."""
        if shape in self._shapes or shape == OVERFLOW_SHAPE:
            return shape
        if len(self._shapes) >= self.max_shapes:
            METRICS.inc("cost_shapes_dropped_total")
            return OVERFLOW_SHAPE
        return shape

    def record(self, rec: dict) -> None:
        with self._lock:
            shape = self._guard(rec.get("shape", UNCLASSIFIED))
            st = self._shapes.get(shape)
            if st is None:
                st = self._shapes[shape] = _ShapeStats()
            st.record(rec)
            self.records_total += 1

    def merge(self, other: "Aggregator") -> "Aggregator":
        with other._lock:
            shapes = {s: st for s, st in other._shapes.items()}
            n = other.records_total
        with self._lock:
            for shape, st in shapes.items():
                shape = self._guard(shape)
                mine = self._shapes.get(shape)
                if mine is None:
                    mine = self._shapes[shape] = _ShapeStats()
                mine.count += st.count
                for f in DIGEST_FIELDS:
                    mine.digests[f].merge(st.digests[f])
                for f in FEATURE_FIELDS:
                    mine.features[f] += st.features[f]
            self.records_total += n
        return self

    def to_doc(self, top_n: int = 10) -> dict:
        """The /debug/costs document: per-shape percentiles + feature
        means, and the top-N most expensive shapes by total µs spent."""
        with self._lock:
            shapes = {}
            for shape, st in self._shapes.items():
                shapes[shape] = {
                    "count": st.count,
                    "features": {f: round(st.features[f]
                                          / max(st.count, 1), 2)
                                 for f in FEATURE_FIELDS
                                 if st.features[f]},
                    "costs": {
                        f: {"p50": d.percentile(0.50),
                            "p90": d.percentile(0.90),
                            "p99": d.percentile(0.99),
                            "max": d.max, "sum": d.sum}
                        for f, d in st.digests.items() if d.sum},
                }
            top = sorted(
                self._shapes,
                key=lambda s: self._shapes[s].digests["total_us"].sum,
                reverse=True)[:top_n]
            return {"records_total": self.records_total,
                    "shapes": shapes,
                    "top": [{"shape": s,
                             "total_us_sum":
                                 self._shapes[s].digests["total_us"].sum,
                             "count": self._shapes[s].count}
                            for s in top]}

    # -- persistence (next to the checkpoint dir) -----------------------------
    def to_state(self) -> dict:
        with self._lock:
            return {"version": 1, "records_total": self.records_total,
                    "shapes": {
                        s: {"count": st.count,
                            "features": dict(st.features),
                            "digests": {f: d.to_dict()
                                        for f, d in st.digests.items()}}
                        for s, st in self._shapes.items()}}

    @classmethod
    def from_state(cls, state: dict,
                   max_shapes: int = MAX_LABEL_SETS) -> "Aggregator":
        agg = cls(max_shapes=max_shapes)
        agg.records_total = int(state.get("records_total", 0))
        for shape, sd in state.get("shapes", {}).items():
            st = _ShapeStats()
            st.count = int(sd.get("count", 0))
            for f, dd in sd.get("digests", {}).items():
                if f in st.digests:
                    st.digests[f] = Digest.from_dict(dd)
            for f, v in sd.get("features", {}).items():
                if f in st.features:
                    st.features[f] = int(v)
            agg._shapes[shape] = st
        return agg

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_state(), f)

    def load(self, path: str) -> bool:
        """Merge a persisted aggregate into this one (restart path).
        A missing file is a silent no-op; a corrupt/truncated or
        wrong-shaped one (a kill mid-write, a bad disk) is COUNTED and
        logged but still never aborts the boot — cost history is
        telemetry, the store starts fresh (ISSUE-11 sidecar
        hardening)."""
        try:
            with open(path) as f:
                state = json.load(f)
            self.merge(Aggregator.from_state(state))
        except OSError:
            return False
        except Exception:  # noqa: BLE001 — corrupt sidecar: start fresh
            import os

            from dgraph_tpu.utils import logging as xlog
            from dgraph_tpu.utils.metrics import METRICS
            METRICS.inc("sidecar_load_failures_total",
                        file=os.path.basename(path))
            xlog.get("costprofile").warning(
                "corrupt cost-profile sidecar %s ignored; starting "
                "with an empty aggregate", path, exc_info=True)
            return False
        return True

    def clear(self) -> None:
        with self._lock:
            self._shapes.clear()
            self.records_total = 0


# -- module-level ambient recorder (METRICS-style process singletons) --------

COSTS = Aggregator()
# per-tablet (predicate) cost sums in µs-equivalents: measured kernel
# execute + ELL build µs where available, a modeled µs for host
# expansions. Bounded metrics-style (cap + "other"); ships to Zero in
# the health heartbeat so tablet moves prefer under-loaded groups.
_TABLET_COSTS: dict[str, int] = {}
# per-mesh-shard cost sums (same µs-equivalent scale; bounded the same
# way) — the residency/balance signal for the sharded serving path
_SHARD_COSTS: dict[str, int] = {}
_TABLET_LOCK = locks.make_lock("costprofile.tablets")
_RECENT: list = []            # ring of finished records (lock-guarded)
_RECENT_LOCK = locks.make_lock("costprofile.recent")
_SINKS: list = []             # push-pipeline subscribers
_TLS = threading.local()
_ENABLED = True


def set_enabled(flag: bool) -> None:
    """Disarm recording (the switch the <5% overhead guard flips);
    aggregates already collected keep serving."""
    global _ENABLED
    _ENABLED = bool(flag)


def active() -> Recorder | None:
    return getattr(_TLS, "rec", None)


def _classify(e: BaseException) -> str:
    if isinstance(e, dl.DeadlineExceeded):
        return "deadline"
    if isinstance(e, dl.Cancelled):
        return "cancelled"
    # by name: admission lives above utils in the layer order
    if type(e).__name__ == "ServerOverloaded":
        return "shed"
    return "error"


@contextlib.contextmanager
def profile(lane: str):
    """Open the request's ambient Recorder (Alpha._request's shell).
    Nested server calls ride the outer recorder, mirroring the outer
    budget/token they already ride; classification mirrors the
    lifecycle contract: shed/deadline/cancelled/error vs ok."""
    if not _ENABLED or getattr(_TLS, "rec", None) is not None:
        yield None
        return
    rec = Recorder(lane)
    _TLS.rec = rec
    outcome = "ok"
    try:
        yield rec
    except BaseException as e:
        outcome = _classify(e)
        raise
    finally:
        _TLS.rec = None
        _finish(rec, outcome)


def _finish(rec: Recorder, outcome: str) -> None:
    from dgraph_tpu.utils import tracing
    record = rec.finish(outcome)
    COSTS.record(record)
    with _RECENT_LOCK:
        _RECENT.append(record)
        if len(_RECENT) > _RECENT_MAX:
            del _RECENT[: len(_RECENT) - _RECENT_MAX]
    METRICS.inc("cost_records_total", outcome=outcome)
    if tracing.enabled():
        # the record's span form: a zero-width child of the request's
        # trace, so /debug/traces?trace_id= shows the joined costs
        with tracing.span("query.cost", shape=record["shape"],
                          outcome=outcome,
                          total_us=record["total_us"],
                          edges=record["edges_traversed"],
                          rpc_legs=record["rpc_legs"]):
            pass
    if _SINKS:
        for sink in tuple(_SINKS):
            try:
                sink(record)
            except Exception:  # noqa: BLE001 — a sink must never fail a request
                pass


# cheap contributor entry points: one TLS load + None check when idle
def note(field: str, value) -> None:
    rec = getattr(_TLS, "rec", None)
    if rec is not None:
        rec.note(field, value)


def add(field: str, delta) -> None:
    rec = getattr(_TLS, "rec", None)
    if rec is not None:
        rec.add(field, delta)


def add_shape(component: str) -> None:
    rec = getattr(_TLS, "rec", None)
    if rec is not None:
        rec.add_shape(component)


def note_max(field: str, value) -> None:
    rec = getattr(_TLS, "rec", None)
    if rec is not None:
        rec.note_max(field, value)


def add_kernel(family: str, compile_us: float = 0.0,
               execute_us: float = 0.0) -> None:
    rec = getattr(_TLS, "rec", None)
    if rec is not None:
        rec.add_kernel(family, compile_us=compile_us,
                       execute_us=execute_us)


def note_launch(start_t: float, end_t: float) -> None:
    rec = getattr(_TLS, "rec", None)
    if rec is not None:
        rec.note_launch(start_t, end_t)


@contextlib.contextmanager
def launch_frame():
    """Module-level form of `Recorder.launch_frame` for contributor
    sites that don't hold the recorder (`Alpha._request`'s nested
    branch, the upsert query leg): a no-op when no request is being
    profiled."""
    rec = getattr(_TLS, "rec", None)
    if rec is None:
        yield
        return
    with rec.launch_frame():
        yield


def add_tablet_cost(pred: str, us) -> None:
    """Charge `us` µs-equivalents of work to a predicate's tablet (the
    placement signal — see _TABLET_COSTS). Cheap: one lock + dict add
    per kernel launch / level expansion, gated on the same switch the
    <5% overhead guard flips."""
    if not _ENABLED:
        return
    with _TABLET_LOCK:
        if pred not in _TABLET_COSTS \
                and len(_TABLET_COSTS) >= MAX_LABEL_SETS:
            pred = OVERFLOW_SHAPE
        _TABLET_COSTS[pred] = _TABLET_COSTS.get(pred, 0) + int(us)


def tablet_costs() -> dict[str, int]:
    """Per-tablet cost sums since process start (heartbeat payload)."""
    with _TABLET_LOCK:
        return dict(_TABLET_COSTS)


def add_shard_cost(shard, us) -> None:
    """Charge `us` µs-equivalents of mesh work to one device shard —
    the shard-keyed twin of add_tablet_cost: tablet sums drive Zero's
    group placement, shard sums drive the MESH residency/balance view
    (/debug/scheduler) so admission and placement see mesh work."""
    if not _ENABLED:
        return
    key = str(shard)
    with _TABLET_LOCK:
        if key not in _SHARD_COSTS \
                and len(_SHARD_COSTS) >= MAX_LABEL_SETS:
            key = OVERFLOW_SHAPE
        _SHARD_COSTS[key] = _SHARD_COSTS.get(key, 0) + int(us)


def shard_costs() -> dict[str, int]:
    """Per-mesh-shard cost sums since process start (scheduler view)."""
    with _TABLET_LOCK:
        return dict(_SHARD_COSTS)


def recent(n: int = 100) -> list[dict]:
    with _RECENT_LOCK:
        return _RECENT[-n:]


def add_sink(fn) -> None:
    """Subscribe to finished records (the live push pipeline). Sinks
    must be non-blocking: they run on the request thread."""
    if fn not in _SINKS:
        _SINKS.append(fn)


def remove_sink(fn) -> None:
    with contextlib.suppress(ValueError):
        _SINKS.remove(fn)


def summary(top_n: int = 10) -> dict:
    """The BENCH-JSON / debug summary of the process aggregate."""
    return COSTS.to_doc(top_n=top_n)


def save(path: str) -> None:
    COSTS.save(path)


def load(path: str) -> bool:
    return COSTS.load(path)


def reset() -> None:
    """Test hook: forget aggregates, recent ring, and sinks."""
    COSTS.clear()
    with _RECENT_LOCK:
        _RECENT.clear()
    with _TABLET_LOCK:
        _TABLET_COSTS.clear()
        _SHARD_COSTS.clear()
    del _SINKS[:]
