"""Typed configuration + superflag parsing.

Reference parity: `x/flags.go` (`z.SuperFlag` grouped flags like
`--badger compression=zstd;numgoroutines=8`) and the cobra/viper flag
surface of `dgraph alpha|zero` (SURVEY §5 config system). One dataclass
per process role; values come from defaults < config file (JSON/TOML-lite)
< CLI flags.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field


def parse_superflag(s: str) -> dict[str, str]:
    """'a=1; b=x' → {'a': '1', 'b': 'x'} (reference: z.SuperFlag)."""
    out = {}
    for part in s.split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"superflag needs key=value, got {part!r}")
        k, v = part.split("=", 1)
        out[k.strip()] = v.strip()
    return out


@dataclass
class AlphaConfig:
    """`dgraph_tpu alpha` (reference: dgraph/cmd/alpha/run.go flags)."""

    p_dir: str = "p"              # posting checkpoint dir
    http_addr: str = "127.0.0.1"
    http_port: int = 8080
    grpc_port: int = 9080
    device_threshold: int = 512   # frontier size that moves a hop on-device
    mesh_devices: int = 0         # 0 = no mesh; -1 = all devices; N = N
    rollup_every: int = 64        # commits between automatic rollups
    memory_budget_mb: int = 0     # 0 = fully resident; >0 = out-of-core
                                  # tablet faulting under this budget
    # unified cache governor (utils/memgov.py): 0 disarms a kind;
    # armed, every byte-holding cache (fused programs, ELL plans,
    # device relations, tablets, LazyPreds residency) evicts above
    # 90% of the budget down to 70%, lowest recompute-value/byte first
    device_budget_mb: int = 0     # HBM-resident cache budget
    host_cache_budget_mb: int = 0  # host-RAM cache budget
    # background maintenance scheduler (store/maintenance.py):
    rollup_after: int = 0         # fold when this many delta layers are
                                  # pending (0 = no background rollup)
    checkpoint_every_s: float = 0.0  # periodic checkpoint+WAL-truncate
                                     # period in seconds (0 = off)
    maintenance_pacing_ms: float = 0.0  # sleep between tablets of a
                                        # maintenance job (serving gets
                                        # the disk/CPU back in between)
    # admission control + request lifecycle (server/admission.py,
    # utils/deadline.py):
    max_inflight: int = 0         # per-lane concurrent-request tokens
                                  # (0 = admission control off)
    queue_depth: int = 16         # bounded FIFO wait queue per lane;
                                  # full queue sheds (ServerOverloaded)
    default_deadline_ms: float = 0.0  # budget for requests that bring
                                      # none (0 = unbounded)
    cost_priors: bool = True      # per-shape cost priors drive admission
                                  # shedding/hints, batch-plan ordering,
                                  # and the placement heartbeat
                                  # (utils/costprior.py); False restores
                                  # count/EMA-only scheduling
    # peer-failure resilience (cluster/resilience.py):
    rpc_retries: int = 2          # re-attempts per retryable cluster RPC
                                  # (transport failures only; backoff is
                                  # capped by the request budget)
    breaker_threshold: int = 5    # consecutive transport failures that
                                  # open a peer's circuit breaker
    breaker_cooldown_ms: float = 500.0  # open-breaker cool-down before
                                        # the half-open probe (jittered,
                                        # doubling per re-open)
    trace_export: str = ""        # write the span registry as
                                  # OTLP/JSON here on shutdown
    # flight recorder + watchdog (utils/flightrec.py): always-on black
    # box; diagnostic bundles land in diag_dir ("" = <p_dir>/diag)
    diag_dir: str = ""
    stall_factor: float = 10.0    # convict a request at factor × its
                                  # costprior prediction (fallback:
                                  # lane EMA, then stall_floor_ms)
    stall_floor_ms: float = 500.0  # prediction fallback + the floor a
                                   # conviction threshold never drops
                                   # below
    # live telemetry push (utils/push.py): stream spans + cost records
    # to an OTLP collector while serving (unset = graceful no-op)
    telemetry_push_url: str = ""      # collector base URL (…/v1/traces)
    telemetry_push_interval_s: float = 5.0  # batch flush cadence
    encryption_key_file: str = ""  # at-rest AES key (reference: ee enc)
    encryption_strict: bool = False  # reject plaintext files once migrated
    slow_query_ms: int = 0        # log queries slower than this (0 = off)
    # time-series telemetry + SLO engine (utils/timeseries.py,
    # utils/slo.py): retained metrics history sampled from the shared
    # registry, multi-window burn-rate alerting, and the load forecast
    # that feeds admission's predicted-load shedding
    ts_interval_s: float = 1.0    # sampler cadence (0 = sampler off)
    ts_ring_points: int = 3600    # retained samples (memgov-governed)
    slo_spec: str = ""            # superflag overrides of the default
                                  # SLO budgets, e.g.
                                  # "read_latency_p99_us=5000;
                                  #  error_rate=0.01"
    forecast_shedding: bool = True  # trend forecast (arrival rate ×
                                    # predicted cost) sheds ahead of the
                                    # queue filling; False restores the
                                    # reactive-only admission path
    trace_dir: str = ""           # arm jax.profiler device-trace capture
    log_level: str = "info"


@dataclass
class ZeroConfig:
    """`dgraph_tpu zero` (reference: dgraph/cmd/zero/run.go flags)."""

    grpc_port: int = 5080
    first_uid: int = 1
    first_ts: int = 1
    log_level: str = "info"


def load_config(cls, path: str | None = None, overrides: dict | None = None):
    """defaults < json file < overrides (reference: viper precedence)."""
    cfg = cls()
    if path and os.path.exists(path):
        with open(path) as f:
            data = json.load(f)
        for k, v in data.items():
            if hasattr(cfg, k):
                setattr(cfg, k, v)
    for k, v in (overrides or {}).items():
        if v is not None and hasattr(cfg, k):
            fieldtype = type(getattr(cfg, k))
            if fieldtype is bool and isinstance(v, str):
                # bool("false") is True — parse by word, and REJECT
                # unrecognized input (a typo must not silently disable
                # a security knob; reference: strconv.ParseBool errors)
                low = v.strip().lower()
                if low in ("1", "true", "yes", "on"):
                    v = True
                elif low in ("0", "false", "no", "off"):
                    v = False
                else:
                    raise ValueError(
                        f"invalid boolean {v!r} for config key {k!r}")
            setattr(cfg, k, fieldtype(v))
    return cfg
