"""Tracing: per-request trace ids, per-hop spans + device profiling.

Reference parity: OpenCensus spans around each `ProcessTaskOverNetwork`
leg with Jaeger export (SURVEY §5). TPU equivalent: lightweight in-process
spans (queryable ring buffer + per-trace index, served by
`/debug/traces` and — as Chrome trace-event JSON, Perfetto-loadable —
`/debug/events`) and `jax.profiler` trace capture for device timelines
when a trace directory is set. Spans fence device work with
`jax.effects_barrier` so timings are honest.

Identity model: every span gets a process-unique integer `span_id`;
nesting is a thread-local STACK of span ids, so concurrent (or nested)
spans that share a name can never alias each other — the historical
name-keyed parent tracking did exactly that. A span belongs to the
trace id established by the enclosing `trace()` context (one per
request on the serving path); spans opened outside any trace carry
trace_id "" and only live in the ring buffer.

`set_enabled(False)` turns span recording into a near-no-op (one flag
check) — the observability layer must never become the regression
(tier-1 guards the query-path overhead at <5%).
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
import uuid
from collections import OrderedDict, deque
from dataclasses import dataclass, field

from dgraph_tpu.utils import locks

_TRACE_DIR: str | None = None
_BUF: deque = deque(maxlen=4096)
_TRACES: "OrderedDict[str, list]" = OrderedDict()
_MAX_TRACES = 256          # retained per-trace span lists
_MAX_TRACE_SPANS = 4096    # spans retained per trace
_LOCK = locks.make_lock("tracing.registry")
_TLS = threading.local()
# span ids must stay unique when spans from SEVERAL processes merge into
# one trace (cross-process propagation, /debug/fleet): the counter is
# salted with the pid in the high bits, so a worker span's parent_id
# (a coordinator-issued id forwarded over gRPC metadata) can never
# collide with a locally-issued id. CPython: count.__next__ is atomic.
_PID = os.getpid()
_IDS = itertools.count(((_PID & 0xFFFF) << 40) | 1)
_ENABLED = True
_SINKS: list = []          # live-export subscribers (utils/push.py)
# cross-process trace-health counters (the bench "fleet" block):
# spans recorded, and spans recorded under a PROPAGATED (attach'd)
# trace context — both under _LOCK with the registries
_STAT = {"spans": 0, "propagated": 0}


@dataclass
class Span:
    name: str
    span_id: int = 0
    parent_id: int = 0          # 0 = root of its thread's stack
    trace_id: str = ""          # "" = outside any trace() context
    start_us: int = 0           # wall-clock epoch µs (Chrome `ts`)
    dur_us: int = 0
    tid: int = 0                # OS thread id (Chrome track)
    pid: int = 0                # OS process id (Chrome process row)
    attrs: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"name": self.name, "span_id": self.span_id,
                "parent_id": self.parent_id, "trace_id": self.trace_id,
                "start_us": self.start_us, "dur_us": self.dur_us,
                "tid": self.tid, "pid": self.pid,
                "attrs": dict(self.attrs)}


# reused sink for disabled spans: callers may still write attrs into it
_NULL_SPAN = Span(name="")


def set_enabled(flag: bool) -> None:
    """Globally arm/disarm span recording (metrics have their own
    switch). Disabled spans cost one attribute load per enter/exit."""
    global _ENABLED
    _ENABLED = bool(flag)


def enabled() -> bool:
    return _ENABLED


def enable_device_trace(trace_dir: str) -> None:
    """Arm jax.profiler capture for the next `span(..., device=True)`."""
    global _TRACE_DIR
    _TRACE_DIR = trace_dir


# -- on-demand device profiling (POST /debug/profile) ------------------------
# jax.profiler trace capture is process-global and NOT reentrant:
# start/stop are single-flight behind a lock, so two operators hitting
# /debug/profile concurrently can never corrupt a capture.
_PROFILE_LOCK = locks.make_lock("tracing.profile")
_PROFILE_DIR: str | None = None


def profile_start(trace_dir: str | None = None) -> str:
    """Start a jax.profiler trace capture under `trace_dir` (default:
    the dir `enable_device_trace`/`--trace_dir` armed). Raises when no
    dir is configured or a capture is already running (single-flight).
    Returns the capture dir."""
    from dgraph_tpu.utils.metrics import METRICS
    global _PROFILE_DIR
    d = trace_dir or _TRACE_DIR
    if not d:
        raise ValueError("no trace dir configured — start the server "
                         "with --trace_dir or pass {\"dir\": ...}")
    with _PROFILE_LOCK:
        if _PROFILE_DIR is not None:
            raise RuntimeError(
                f"a device profile is already capturing under "
                f"{_PROFILE_DIR} — stop it first (single-flight)")
        import jax
        jax.profiler.start_trace(d)
        _PROFILE_DIR = d
        METRICS.inc("device_profile_captures_total", outcome="started")
        return d


def profile_stop() -> str:
    """Stop the running capture and return its dir; the XLA-level
    timeline lands under `<dir>/plugins/profile/` (Perfetto/
    TensorBoard-loadable)."""
    from dgraph_tpu.utils.metrics import METRICS
    global _PROFILE_DIR
    with _PROFILE_LOCK:
        if _PROFILE_DIR is None:
            raise RuntimeError("no device profile is running")
        d, _PROFILE_DIR = _PROFILE_DIR, None
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception:
            METRICS.inc("device_profile_captures_total",
                        outcome="error")
            raise
        METRICS.inc("device_profile_captures_total", outcome="ok")
        return d


def profile_status() -> dict:
    with _PROFILE_LOCK:
        return {"running": _PROFILE_DIR is not None,
                "dir": _PROFILE_DIR}


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def current_trace_id() -> str:
    return getattr(_TLS, "trace_id", "")


def current_span_id() -> int:
    """The innermost open span's id on this thread (0 = none) — what an
    outbound RPC forwards as the remote child's parent id."""
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else 0


@contextlib.contextmanager
def attach(trace_id: str, parent_id: int = 0):
    """Re-establish a PROPAGATED trace context on this thread: spans
    opened inside index under `trace_id`, and (when `parent_id` is
    given) parent to that FOREIGN span id — so a worker-side handler's
    spans become genuine children of the coordinator's request trace,
    and a maintenance job joins the admin request that triggered it.
    Empty `trace_id` is a no-op (the common un-traced RPC path)."""
    if not trace_id:
        yield
        return
    from dgraph_tpu.utils.metrics import METRICS
    METRICS.inc("trace_propagated_total")
    prev = getattr(_TLS, "trace_id", "")
    _TLS.trace_id = trace_id
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    pushed = bool(parent_id)
    if pushed:
        stack.append(parent_id)
    _TLS.attach_depth = getattr(_TLS, "attach_depth", 0) + 1
    try:
        yield
    finally:
        _TLS.attach_depth -= 1
        if pushed:
            stack.pop()
        _TLS.trace_id = prev


@contextlib.contextmanager
def trace(name: str = "request", trace_id: str | None = None, **attrs):
    """Establish a trace context: every span opened on this thread while
    inside (the root `name` span included) is indexed under the yielded
    trace id — the id the serving path echoes to clients and
    `/debug/traces?trace_id=` resolves."""
    tid = trace_id or new_trace_id()
    prev = getattr(_TLS, "trace_id", "")
    _TLS.trace_id = tid
    try:
        with span(name, **attrs):
            yield tid
    finally:
        _TLS.trace_id = prev


@contextlib.contextmanager
def span(name: str, device: bool = False, **attrs):
    """Time a region; nests via a thread-local stack of span IDS (names
    never participate in parent tracking — same-name spans, nested or
    concurrent, stay distinct). Yields the Span so callers can attach
    attrs discovered mid-region (edge counts, chosen code path).

    `device=True` additionally wraps the region in a jax.profiler trace
    (if armed) and blocks on async dispatch before closing the span.
    """
    if not _ENABLED and not device:
        yield _NULL_SPAN
        return
    sid = next(_IDS)
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    s = Span(name=name, span_id=sid,
             parent_id=stack[-1] if stack else 0,
             trace_id=getattr(_TLS, "trace_id", ""),
             # graftlint: allow(wall-clock): span start is an EPOCH timestamp —
             # Perfetto/OTLP exports align traces across processes by wall clock
             start_us=int(time.time() * 1e6),
             tid=threading.get_ident(), pid=_PID, attrs=attrs)
    stack.append(sid)
    t0 = time.perf_counter()
    prof = None
    if device and _TRACE_DIR is not None:
        import jax
        prof = jax.profiler.trace(_TRACE_DIR)
        prof.__enter__()
    try:
        yield s
    finally:
        if device:
            import jax
            # fence pending async work so dur_us covers real execution
            jax.effects_barrier()
        if prof is not None:
            prof.__exit__(None, None, None)
        stack.pop()
        s.dur_us = int((time.perf_counter() - t0) * 1e6)
        propagated = getattr(_TLS, "attach_depth", 0) > 0
        with _LOCK:
            _STAT["spans"] += 1
            if propagated:
                _STAT["propagated"] += 1
            _BUF.append(s)
            if s.trace_id:
                spans = _TRACES.get(s.trace_id)
                if spans is None:
                    spans = _TRACES[s.trace_id] = []
                    while len(_TRACES) > _MAX_TRACES:
                        _TRACES.popitem(last=False)
                if len(spans) < _MAX_TRACE_SPANS:
                    spans.append(s)
        if _SINKS:
            # live push (outside the lock): sinks buffer-and-return —
            # the request path never blocks on a collector
            for sink in tuple(_SINKS):
                try:
                    sink(s)
                except Exception:  # noqa: BLE001 — a sink must never fail a span
                    pass


def add_sink(fn) -> None:
    """Subscribe to completed spans (the live push pipeline). Sinks run
    on the closing thread and must be non-blocking."""
    if fn not in _SINKS:
        _SINKS.append(fn)


def remove_sink(fn) -> None:
    with contextlib.suppress(ValueError):
        _SINKS.remove(fn)


def recent(n: int = 100) -> list[Span]:
    with _LOCK:
        return list(_BUF)[-n:]


def trace_spans(trace_id: str) -> list[Span]:
    """Completed spans of one trace, in completion order (children close
    before parents, so the root span is last)."""
    with _LOCK:
        return list(_TRACES.get(trace_id, ()))


def stats() -> dict:
    """Cross-process trace health: spans recorded and the fraction
    recorded under a propagated (attach'd) trace context — the bench
    "fleet" block and the /debug/fleet per-node fragments read this."""
    with _LOCK:
        spans, prop = _STAT["spans"], _STAT["propagated"]
    return {"spans_total": spans, "propagated_total": prop,
            "propagated_frac": round(prop / spans, 4) if spans else 0.0}


def to_chrome(spans: list[Span]) -> dict:
    """Chrome trace-event JSON (the `ph:"X"` complete-event form) —
    loadable in Perfetto / chrome://tracing. Span attrs ride in `args`;
    ts/dur are µs as the format requires."""
    return {
        "displayTimeUnit": "ms",
        "traceEvents": [
            {"name": s.name, "cat": "dgraph_tpu", "ph": "X",
             "ts": s.start_us, "dur": max(s.dur_us, 1),
             # each originating process is its own Perfetto process row,
             # so a merged cross-process trace renders both sides on one
             # timeline (historical spans without a pid fold under 1)
             "pid": s.pid or 1, "tid": s.tid,
             "args": {**{k: _jsonable(v) for k, v in s.attrs.items()},
                      "span_id": s.span_id, "parent_id": s.parent_id,
                      "trace_id": s.trace_id}}
            for s in spans],
    }


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


# -- OTLP/JSON export (ROADMAP: span export to an external collector) --------

def _otlp_any(v) -> dict:
    """Python value → OTLP AnyValue (the typed union OTLP mandates)."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}  # OTLP/JSON carries int64 as string
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": v if isinstance(v, str) else str(v)}


def _from_otlp_any(d: dict):
    if "boolValue" in d:
        return bool(d["boolValue"])
    if "intValue" in d:
        return int(d["intValue"])
    if "doubleValue" in d:
        return float(d["doubleValue"])
    return d.get("stringValue", "")


def _otlp_trace_id(tid: str) -> str:
    """Our 16-hex trace ids → the 32-hex (16-byte) ids OTLP requires.
    Left-padded with zeros; non-hex ids (tests pass arbitrary strings)
    fall back to a hex encoding of the string bytes."""
    if not tid:
        return "0" * 32
    try:
        return f"{int(tid, 16):032x}"
    except ValueError:
        return tid.encode().hex()[:32].ljust(32, "0")


def to_otlp(spans: list[Span]) -> dict:
    """OTLP/JSON (`ExportTraceServiceRequest` shape) — POSTable to any
    collector's `/v1/traces` as-is. Span ids hex-encode to the 8-byte
    spanId field; nanosecond timestamps derive from start_us + dur_us;
    attrs become typed keyValue pairs. The raw registry identifiers
    also ride as `dgraph.*` attributes so `from_otlp` round-trips
    losslessly (the round-trip test pins this)."""
    out = []
    for s in spans:
        attrs = [{"key": k, "value": _otlp_any(_jsonable(v))}
                 for k, v in s.attrs.items()]
        attrs.append({"key": "dgraph.trace_id",
                      "value": {"stringValue": s.trace_id}})
        attrs.append({"key": "dgraph.tid",
                      "value": {"intValue": str(s.tid)}})
        attrs.append({"key": "dgraph.pid",
                      "value": {"intValue": str(s.pid)}})
        out.append({
            "traceId": _otlp_trace_id(s.trace_id),
            "spanId": f"{s.span_id:016x}",
            "parentSpanId": (f"{s.parent_id:016x}" if s.parent_id
                             else ""),
            "name": s.name,
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(s.start_us * 1000),
            "endTimeUnixNano": str((s.start_us + s.dur_us) * 1000),
            "attributes": attrs,
        })
    return {"resourceSpans": [{
        "resource": {"attributes": [
            {"key": "service.name",
             "value": {"stringValue": "dgraph_tpu"}}]},
        "scopeSpans": [{"scope": {"name": "dgraph_tpu"},
                        "spans": out}],
    }]}


def from_otlp(doc: dict) -> list[Span]:
    """Inverse of `to_otlp` (the round-trip contract): rebuild Span
    objects from an OTLP/JSON document."""
    spans = []
    for rs in doc.get("resourceSpans", ()):
        for ss in rs.get("scopeSpans", ()):
            for o in ss.get("spans", ()):
                attrs, tid, os_tid, os_pid = {}, "", 0, 0
                for kv in o.get("attributes", ()):
                    v = _from_otlp_any(kv.get("value", {}))
                    if kv["key"] == "dgraph.trace_id":
                        tid = v
                    elif kv["key"] == "dgraph.tid":
                        os_tid = int(v)
                    elif kv["key"] == "dgraph.pid":
                        os_pid = int(v)
                    else:
                        attrs[kv["key"]] = v
                start_us = int(o["startTimeUnixNano"]) // 1000
                spans.append(Span(
                    name=o["name"],
                    span_id=int(o["spanId"], 16),
                    parent_id=(int(o["parentSpanId"], 16)
                               if o.get("parentSpanId") else 0),
                    trace_id=tid,
                    start_us=start_us,
                    dur_us=int(o["endTimeUnixNano"]) // 1000 - start_us,
                    tid=os_tid, pid=os_pid, attrs=attrs))
    return spans


def export_otlp(path: str, spans: list[Span] | None = None) -> int:
    """Write the span registry (default: the full ring buffer) as
    OTLP/JSON to `path` — the `--trace_export` flag's shutdown hook and
    an offline bridge to collectors. Returns the span count."""
    import json
    if spans is None:
        spans = recent(len(_BUF))
    with open(path, "w") as f:
        json.dump(to_otlp(spans), f)
    return len(spans)


def clear() -> None:
    with _LOCK:
        _BUF.clear()
        _TRACES.clear()
        _STAT["spans"] = _STAT["propagated"] = 0
