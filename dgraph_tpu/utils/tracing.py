"""Tracing: per-request trace ids, per-hop spans + device profiling.

Reference parity: OpenCensus spans around each `ProcessTaskOverNetwork`
leg with Jaeger export (SURVEY §5). TPU equivalent: lightweight in-process
spans (queryable ring buffer + per-trace index, served by
`/debug/traces` and — as Chrome trace-event JSON, Perfetto-loadable —
`/debug/events`) and `jax.profiler` trace capture for device timelines
when a trace directory is set. Spans fence device work with
`jax.effects_barrier` so timings are honest.

Identity model: every span gets a process-unique integer `span_id`;
nesting is a thread-local STACK of span ids, so concurrent (or nested)
spans that share a name can never alias each other — the historical
name-keyed parent tracking did exactly that. A span belongs to the
trace id established by the enclosing `trace()` context (one per
request on the serving path); spans opened outside any trace carry
trace_id "" and only live in the ring buffer.

`set_enabled(False)` turns span recording into a near-no-op (one flag
check) — the observability layer must never become the regression
(tier-1 guards the query-path overhead at <5%).
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
import uuid
from collections import OrderedDict, deque
from dataclasses import dataclass, field

_TRACE_DIR: str | None = None
_BUF: deque = deque(maxlen=4096)
_TRACES: "OrderedDict[str, list]" = OrderedDict()
_MAX_TRACES = 256          # retained per-trace span lists
_MAX_TRACE_SPANS = 4096    # spans retained per trace
_LOCK = threading.Lock()
_TLS = threading.local()
_IDS = itertools.count(1)  # CPython: count.__next__ is atomic
_ENABLED = True


@dataclass
class Span:
    name: str
    span_id: int = 0
    parent_id: int = 0          # 0 = root of its thread's stack
    trace_id: str = ""          # "" = outside any trace() context
    start_us: int = 0           # wall-clock epoch µs (Chrome `ts`)
    dur_us: int = 0
    tid: int = 0                # OS thread id (Chrome track)
    attrs: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"name": self.name, "span_id": self.span_id,
                "parent_id": self.parent_id, "trace_id": self.trace_id,
                "start_us": self.start_us, "dur_us": self.dur_us,
                "tid": self.tid, "attrs": dict(self.attrs)}


# reused sink for disabled spans: callers may still write attrs into it
_NULL_SPAN = Span(name="")


def set_enabled(flag: bool) -> None:
    """Globally arm/disarm span recording (metrics have their own
    switch). Disabled spans cost one attribute load per enter/exit."""
    global _ENABLED
    _ENABLED = bool(flag)


def enabled() -> bool:
    return _ENABLED


def enable_device_trace(trace_dir: str) -> None:
    """Arm jax.profiler capture for the next `span(..., device=True)`."""
    global _TRACE_DIR
    _TRACE_DIR = trace_dir


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def current_trace_id() -> str:
    return getattr(_TLS, "trace_id", "")


@contextlib.contextmanager
def trace(name: str = "request", trace_id: str | None = None, **attrs):
    """Establish a trace context: every span opened on this thread while
    inside (the root `name` span included) is indexed under the yielded
    trace id — the id the serving path echoes to clients and
    `/debug/traces?trace_id=` resolves."""
    tid = trace_id or new_trace_id()
    prev = getattr(_TLS, "trace_id", "")
    _TLS.trace_id = tid
    try:
        with span(name, **attrs):
            yield tid
    finally:
        _TLS.trace_id = prev


@contextlib.contextmanager
def span(name: str, device: bool = False, **attrs):
    """Time a region; nests via a thread-local stack of span IDS (names
    never participate in parent tracking — same-name spans, nested or
    concurrent, stay distinct). Yields the Span so callers can attach
    attrs discovered mid-region (edge counts, chosen code path).

    `device=True` additionally wraps the region in a jax.profiler trace
    (if armed) and blocks on async dispatch before closing the span.
    """
    if not _ENABLED and not device:
        yield _NULL_SPAN
        return
    sid = next(_IDS)
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    s = Span(name=name, span_id=sid,
             parent_id=stack[-1] if stack else 0,
             trace_id=getattr(_TLS, "trace_id", ""),
             start_us=int(time.time() * 1e6),
             tid=threading.get_ident(), attrs=attrs)
    stack.append(sid)
    t0 = time.perf_counter()
    prof = None
    if device and _TRACE_DIR is not None:
        import jax
        prof = jax.profiler.trace(_TRACE_DIR)
        prof.__enter__()
    try:
        yield s
    finally:
        if device:
            import jax
            # fence pending async work so dur_us covers real execution
            jax.effects_barrier()
        if prof is not None:
            prof.__exit__(None, None, None)
        stack.pop()
        s.dur_us = int((time.perf_counter() - t0) * 1e6)
        with _LOCK:
            _BUF.append(s)
            if s.trace_id:
                spans = _TRACES.get(s.trace_id)
                if spans is None:
                    spans = _TRACES[s.trace_id] = []
                    while len(_TRACES) > _MAX_TRACES:
                        _TRACES.popitem(last=False)
                if len(spans) < _MAX_TRACE_SPANS:
                    spans.append(s)


def recent(n: int = 100) -> list[Span]:
    with _LOCK:
        return list(_BUF)[-n:]


def trace_spans(trace_id: str) -> list[Span]:
    """Completed spans of one trace, in completion order (children close
    before parents, so the root span is last)."""
    with _LOCK:
        return list(_TRACES.get(trace_id, ()))


def to_chrome(spans: list[Span]) -> dict:
    """Chrome trace-event JSON (the `ph:"X"` complete-event form) —
    loadable in Perfetto / chrome://tracing. Span attrs ride in `args`;
    ts/dur are µs as the format requires."""
    return {
        "displayTimeUnit": "ms",
        "traceEvents": [
            {"name": s.name, "cat": "dgraph_tpu", "ph": "X",
             "ts": s.start_us, "dur": max(s.dur_us, 1),
             "pid": 1, "tid": s.tid,
             "args": {**{k: _jsonable(v) for k, v in s.attrs.items()},
                      "span_id": s.span_id, "parent_id": s.parent_id,
                      "trace_id": s.trace_id}}
            for s in spans],
    }


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def clear() -> None:
    with _LOCK:
        _BUF.clear()
        _TRACES.clear()
