"""Tracing: per-hop spans + device profiling.

Reference parity: OpenCensus spans around each `ProcessTaskOverNetwork`
leg with Jaeger export (SURVEY §5). TPU equivalent: lightweight in-process
spans (queryable buffer + log lines) and `jax.profiler` trace capture for
Perfetto when a trace directory is set. Spans fence device work with
`block_until_ready` so timings are honest.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from dataclasses import dataclass, field

_TRACE_DIR: str | None = None
_BUF: deque = deque(maxlen=4096)
_LOCK = threading.Lock()
_TLS = threading.local()


@dataclass
class Span:
    name: str
    start_us: int
    dur_us: int = 0
    parent: str = ""
    attrs: dict = field(default_factory=dict)


def enable_device_trace(trace_dir: str) -> None:
    """Arm jax.profiler capture for the next `span(..., device=True)`."""
    global _TRACE_DIR
    _TRACE_DIR = trace_dir


@contextlib.contextmanager
def span(name: str, device: bool = False, **attrs):
    """Time a region; nests via thread-local parent tracking.

    `device=True` additionally wraps the region in a jax.profiler trace
    (if armed) and blocks on async dispatch before closing the span.
    """
    parent = getattr(_TLS, "current", "")
    _TLS.current = name
    t0 = time.perf_counter()
    prof = None
    if device and _TRACE_DIR is not None:
        import jax
        prof = jax.profiler.trace(_TRACE_DIR)
        prof.__enter__()
    try:
        yield
    finally:
        if device:
            import jax
            # fence pending async work so dur_us covers real execution
            jax.effects_barrier()
        if prof is not None:
            prof.__exit__(None, None, None)
        _TLS.current = parent
        s = Span(name=name, start_us=int(t0 * 1e6),
                 dur_us=int((time.perf_counter() - t0) * 1e6),
                 parent=parent, attrs=attrs)
        with _LOCK:
            _BUF.append(s)


def recent(n: int = 100) -> list[Span]:
    with _LOCK:
        return list(_BUF)[-n:]


def clear() -> None:
    with _LOCK:
        _BUF.clear()
