"""Flight recorder: always-on black box + predicted-cost watchdog.

The telemetry stack (tracing, cost profiles, metrics, the debug HTTP
surface) answers any question an operator thinks to ASK — but the
chip-window scenario is the opposite: a silent stall with nobody
watching to hit `POST /debug/profile` at the right moment. This module
is the unattended half:

* **Flight ring** — a bounded, lock-disciplined event ring that
  passively taps the existing streams via the PR-8 sink pattern
  (`tracing.add_sink` + `costprofile.add_sink`) plus new `emit()` hook
  sites: admission shed/displace decisions, breaker transitions,
  maintenance job outcomes, storage corruption/heal events. When the
  ring is full the OLDEST event drops, counted in
  `flight_ring_dropped_total{kind=}` — an aircraft black box, not an
  unbounded log.

* **Watchdog daemon** — one background thread that walks the ambient
  in-flight registry (`Alpha._request` registers every request via
  `track_request`; bench stages register via `track` with an explicit
  budget) and convicts anomalies *without per-workload thresholds*:
  the cost priors (utils/costprior.py) predict what a request SHOULD
  cost, so a request running `stall_factor`× past its prediction
  (fallback chain: shape prior → lane EMA → `stall_floor_ms`) IS the
  anomaly. Requests that carry a deadline are judged against the
  deadline instead — cooperative cancellation fires first, so only a
  WEDGED request (past its budget by `grace_s` without reaching a
  checkpoint) is convicted; fault-injected slowness that stays inside
  its (fault-extended) budget never is (the fuzz smokes pin this).
  The watchdog also watches an admission lane's queue head outwaiting
  its service-time slack, a maintenance job that stops advancing
  tablet progress, and a wedged telemetry pusher. Convictions count
  `watchdog_stalls_total{kind=}`.

* **Diagnostic bundle** — on conviction (and on SIGUSR2, a fatal
  error, or `POST /debug/flightrecorder {"action": "dump"}`) one
  self-contained JSON bundle lands in `diag_dir` via
  `vault.atomic_write`: all-thread Python stacks, the flight ring, the
  in-flight registry (each op with its stack, trace spans, and cost
  prediction), a snapshot of EVERY debug surface (traces, events,
  costs, scheduler, admission, locks, races, peers, slow_queries),
  the full metrics exposition, and the server config. Dumps count
  `flight_dumps_total{trigger=}`, are rate-limited (watchdog triggers
  honor `min_dump_interval_s`; operator triggers bypass), and an
  optional single-flight `jax.profiler` capture rides the PR-8
  machinery (`tracing.profile_start/stop` — its lock guarantees never
  two concurrent).

Disarmed (the default for library use), the module starts ZERO
threads, subscribes no sinks, and every hook (`emit`, `track`) is one
global load + None check — the same <5% uncontended-overhead bar the
rest of the observability layer holds (tier-1 guard in
tests/test_flightrec.py).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import sys
import threading
import time
import traceback
from collections import deque

from dgraph_tpu.utils import costprior, costprofile
from dgraph_tpu.utils import deadline as dl
from dgraph_tpu.utils import locks
from dgraph_tpu.utils import logging as xlog
from dgraph_tpu.utils import tracing
from dgraph_tpu.utils.metrics import METRICS

__all__ = ["FlightRing", "Watchdog", "arm", "disarm", "armed", "emit",
           "track", "track_request", "rpc_leg", "rpc_in_flight",
           "flight_snapshot", "dump", "request_dump", "state",
           "dumps", "RING_MAX", "STALL_FACTOR", "STALL_FLOOR_MS"]

RING_MAX = 2048            # events retained in the flight ring
RING_SPAN_MIN_US = 1000    # child spans below this skip the ring
POLL_S = 0.25              # watchdog scan cadence
STALL_FACTOR = 10.0        # conviction at factor × predicted cost
STALL_FLOOR_MS = 500.0     # prediction fallback + conviction floor
GRACE_S = 1.0              # slack past a deadline before "wedged"
MIN_DUMP_INTERVAL_S = 30.0  # watchdog dump rate limit
MAINT_STALL_S = 120.0      # maintenance job with no tablet progress
DUMPS_MAX = 16             # recent-dump records retained
PEER_FLIGHT_BUDGET_MS = 2000.0  # DebugFlight pull budget per conviction


def _now_ms() -> int:
    # graftlint: allow(wall-clock): bundle/ring timestamps CROSS the
    # process boundary — the dump file is read offline, long after this
    # process (and its monotonic epoch) is gone
    return int(time.time() * 1e3)


class FlightRing:
    """Bounded event ring (the black box). One lock, integer-bounded
    memory; a full ring drops its OLDEST event and counts the drop by
    the evicted event's kind."""

    def __init__(self, cap: int = RING_MAX):
        self._lock = locks.make_lock("flightrec.ring")
        self._buf: deque = deque()
        self.cap = int(cap)
        self.added = 0
        locks.guarded(self, "flightrec.ring")

    def add(self, kind: str, fields: dict | None = None) -> None:
        ev = {"kind": kind, "t_ms": _now_ms()}
        if fields:
            ev.update(fields)
        dropped = None
        with self._lock:
            if len(self._buf) >= self.cap:
                dropped = self._buf.popleft()["kind"]
            self._buf.append(ev)
            self.added += 1
        if dropped is not None:
            METRICS.inc("flight_ring_dropped_total", kind=dropped)

    def recent(self, n: int | None = None) -> list[dict]:
        with self._lock:
            buf = list(self._buf)
        return buf if n is None else buf[-n:]

    def stats(self) -> dict:
        with self._lock:
            return {"size": len(self._buf), "cap": self.cap,
                    "added": self.added}


class _Tracked:
    """One registered in-flight operation (a request or a bench
    stage). Plain record: written by its own thread at registration,
    `convicted` flipped only by the single watchdog thread."""

    __slots__ = ("op_id", "name", "lane", "predicted_us", "query",
                 "trace_id", "ident", "started", "budget_deadline",
                 "ctx", "convicted")

    def to_dict(self, now: float) -> dict:
        d = {"name": self.name, "lane": self.lane,
             "elapsed_us": int((now - self.started) * 1e6),
             "predicted_us": self.predicted_us,
             "trace_id": self.trace_id, "query": self.query,
             "convicted": self.convicted}
        deadline = self._deadline()
        if deadline is not None:
            d["budget_remaining_s"] = round(deadline - now, 3)
        return d

    def _deadline(self) -> float | None:
        if self.ctx is not None and self.ctx.deadline is not None:
            return self.ctx.deadline
        return self.budget_deadline


# in-flight registry: module-level like tracing's span ring — the
# watchdog and bundle builder walk it from their own threads
_OPS_LOCK = locks.make_lock("flightrec.ops")
_OPS: dict[int, _Tracked] = {}
_IDS = itertools.count(1)

# outstanding outbound RPC per thread: ident → (peer, rpc, started).
# Single writer per thread (the calling thread itself) + lock-free
# watchdog reads — the same CPython-atomic plain-dict discipline
# utils/deadline.py's _ACTIVE uses. This is how a conviction names the
# wedged PEER: the convicted request's thread is sitting inside a leg.
_RPC_INFLIGHT: dict[int, tuple] = {}

# recent dump records (path/trigger/reason), bundle-independent so the
# HTTP surface and BENCH JSON can list them without re-reading disk
_DUMPS_LOCK = locks.make_lock("flightrec.dumps")
_DUMPS: list[dict] = []

_STATE = None          # _State | None — armed configuration
_PREV_SIG = None       # previous SIGUSR2 handler (restored on disarm)


class Watchdog:
    """The anomaly scanner (see module doc). One daemon thread; all
    mutable bookkeeping under one lock so the HTTP state() view and
    the scan thread never race."""

    def __init__(self, *, poll_s: float, stall_factor: float,
                 stall_floor_ms: float, grace_s: float,
                 min_dump_interval_s: float, maintenance_stall_s: float,
                 alpha=None, pusher=None):
        self.poll_s = max(float(poll_s), 0.01)
        self.stall_factor = float(stall_factor)
        self.stall_floor_ms = float(stall_floor_ms)
        self.grace_s = float(grace_s)
        self.min_dump_interval_s = float(min_dump_interval_s)
        self.maintenance_stall_s = float(maintenance_stall_s)
        self.alpha = alpha
        self.pusher = pusher
        self._lock = locks.make_lock("flightrec.watchdog")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._dump_now: list[str] = []     # operator-requested triggers
        self._kind_last: dict[str, float] = {}  # per-kind conviction gate
        self._last_dump_mono = float("-inf")
        self._maint_seen = (None, -1, 0.0)  # (job, progress, since)
        # governor sticky-degrade count at last scan; None until the
        # first scan baselines it (a watchdog armed AFTER an old
        # degrade must not convict history)
        self._oom_seen = None
        self.convictions = 0
        self.suppressed = 0
        locks.guarded(self, "flightrec.watchdog")

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "Watchdog":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dgraph-flight-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def request_dump(self, trigger: str) -> None:
        """Queue an operator dump (SIGUSR2 path): the NEXT scan writes
        it from the watchdog thread — a signal handler must never walk
        locks the interrupted frame may hold."""
        with self._lock:
            self._dump_now.append(trigger)

    # -- the scan -------------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — the watchdog must outlive bugs
                xlog.get("flightrec").exception("watchdog tick failed")

    def _tick(self) -> None:
        now = dl.monotonic_s()
        with self._lock:
            pending, self._dump_now = self._dump_now, []
        for trig in pending:
            self._dump(trig, reason={"kind": "requested"}, now=now,
                       force=True)
        convicted: list[tuple[str, dict]] = []
        with _OPS_LOCK:
            ops = list(_OPS.values())
        for op in ops:
            verdict = self._judge(op, now)
            if verdict is not None:
                convicted.append(verdict)
        convicted.extend(self._scan_admission(now))
        convicted.extend(self._scan_maintenance(now))
        convicted.extend(self._scan_pusher())
        convicted.extend(self._scan_memory(now))
        convicted.extend(self._scan_slo(now))
        for kind, detail in convicted:
            METRICS.inc("watchdog_stalls_total", kind=kind)
            emit("watchdog.stall", stall=kind, **{
                k: v for k, v in detail.items()
                if isinstance(v, (str, int, float, bool))})
            self._dump("watchdog", reason={"kind": kind, **detail},
                       now=now)

    def _judge(self, op: _Tracked, now: float):
        """One in-flight op: deadline-carrying ops are judged only
        against their (fault-extended) budget — cooperative
        cancellation fires first, so past-deadline-plus-grace means
        WEDGED, not merely slow. Unbounded ops are judged against
        `stall_factor`× their cost prediction."""
        if op.convicted:
            return None
        deadline = op._deadline()
        if deadline is not None:
            if now > deadline + self.grace_s:
                op.convicted = True
                return ("wedged", {"op": _op_evidence(op, now),
                                   **_peer_leg(op)})
            return None
        base_us = op.predicted_us
        if base_us is None and op.lane:
            base_us = costprior.lane_ema_us(op.lane)
        if base_us is None or base_us <= 0:
            base_us = self.stall_floor_ms * 1e3
        threshold_us = max(self.stall_factor * base_us,
                           self.stall_floor_ms * 1e3)
        if (now - op.started) * 1e6 > threshold_us:
            op.convicted = True
            return ("request", {"threshold_us": int(threshold_us),
                                "op": _op_evidence(op, now),
                                **_peer_leg(op)})
        return None

    def _scan_admission(self, now: float):
        adm = getattr(self.alpha, "admission", None) \
            if self.alpha is not None else None
        if adm is None:
            return []
        out = []
        for lane, hw in adm.head_waits().items():
            slack_s = max(self.stall_factor * hw["service_ema_s"],
                          self.stall_floor_ms / 1e3)
            if hw["wait_s"] > slack_s and self._kind_due("queue_head",
                                                         now):
                out.append(("queue_head", {
                    "lane": lane, "head_wait_s": round(hw["wait_s"], 3),
                    "slack_s": round(slack_s, 3)}))
        return out

    def _scan_maintenance(self, now: float):
        maint = getattr(self.alpha, "maintenance", None) \
            if self.alpha is not None else None
        if maint is None:
            return []
        st = maint.status()
        running, prog = st.get("running"), st.get("progress", 0)
        with self._lock:
            job0, prog0, since = self._maint_seen
            if running is None or running != job0 or prog != prog0:
                self._maint_seen = (running, prog, now)
                return []
            stalled_s = now - since
        if stalled_s > self.maintenance_stall_s \
                and self._kind_due("maintenance", now):
            return [("maintenance", {"job": running, "progress": prog,
                                     "stalled_s": round(stalled_s, 1)})]
        return []

    def _scan_pusher(self):
        p = self.pusher
        if p is None:
            return []
        st = p.status()
        buffered = st.get("buffered_spans", 0) + st.get("buffered_costs",
                                                        0)
        if not buffered:
            return []
        wedge_s = max(3.0 * st.get("interval_s", 5.0),
                      st.get("backoff_s", 0.0) + self.grace_s) \
            + self.grace_s
        dead = not st.get("alive", True)
        stale = st.get("last_cycle_age_s", 0.0) > wedge_s
        if (dead or stale) and self._kind_due("pusher",
                                              dl.monotonic_s()):
            return [("pusher", {"buffered": buffered, "dead": dead,
                                "last_cycle_age_s":
                                    st.get("last_cycle_age_s")})]
        return []

    def _scan_memory(self, now: float):
        """Repeat-OOM conviction (kind=oom): the memory governor
        absorbing a single allocation failure with one evict-retry is
        the design working — no conviction. A shape going
        STICKY-degraded means the allocation failed AGAIN after the
        evict pass (the repeat the budget could not absorb): that is a
        capsized budget the black box should explain — convict once per
        dump interval with the governor's counters as evidence."""
        from dgraph_tpu.utils import memgov
        st = memgov.GOVERNOR.oom_stats()
        with self._lock:
            deg0 = self._oom_seen
            self._oom_seen = st["degraded"]
        if deg0 is None:
            return []  # first scan baselines; history never convicts
        if st["degraded"] > deg0 and self._kind_due("oom", now):
            return [("oom", {"events": st["events"],
                             "retries": st["retries"],
                             "degraded": st["degraded"]})]
        return []

    def _scan_slo(self, now: float):
        """Sustained fast-burn conviction (kind=slo): the SLO engine's
        edge-triggered breach already paged (`slo_breaches_total` + a
        `slo.breach` flight event with an exemplar trace id); a FAST
        burn that stays breached across the engine's sustain threshold
        is an ongoing regression the black box should explain — convict
        once per dump interval, so the bundle's "timeseries" surface
        records the approach, not just the crash."""
        from dgraph_tpu.utils import slo as _slo
        eng = _slo.ENGINE
        if eng is None:
            return []
        out = []
        for c in eng.convictable():
            if self._kind_due("slo", now):
                out.append(("slo", c))
        return out

    def _kind_due(self, kind: str, now: float) -> bool:
        """Condition-shaped convictions (queue head, maintenance,
        pusher) persist across scans — gate re-conviction of the same
        kind on the dump interval so one wedge is one report stream,
        not one per poll."""
        with self._lock:
            if now - self._kind_last.get(kind, float("-inf")) \
                    < self.min_dump_interval_s:
                return False
            self._kind_last[kind] = now
            return True

    # -- dumping --------------------------------------------------------------
    def _dump(self, trigger: str, reason: dict, now: float,
              force: bool = False) -> None:
        with self._lock:
            self.convictions += not force
            if not force and now - self._last_dump_mono \
                    < self.min_dump_interval_s:
                self.suppressed += 1
                return
            self._last_dump_mono = now
        try:
            dump(trigger=trigger, reason=reason, alpha=self.alpha)
        except Exception:  # noqa: BLE001 — a failed dump must not kill the scan
            xlog.get("flightrec").exception("flight dump failed")

    def state(self) -> dict:
        with self._lock:
            return {"armed": True, "poll_s": self.poll_s,
                    "stall_factor": self.stall_factor,
                    "stall_floor_ms": self.stall_floor_ms,
                    "grace_s": self.grace_s,
                    "min_dump_interval_s": self.min_dump_interval_s,
                    "maintenance_stall_s": self.maintenance_stall_s,
                    "convictions": self.convictions,
                    "suppressed": self.suppressed}


class _State:
    """Armed configuration: the ring, the watchdog, sink closures, and
    the dump context. Write-once at arm() — the hooks only read."""

    def __init__(self, ring: FlightRing, diag_dir: str | None, alpha,
                 pusher, config: dict | None, capture_device: bool,
                 on_dump):
        self.ring = ring
        self.diag_dir = diag_dir
        self.alpha = alpha
        self.pusher = pusher
        self.config = dict(config or {})
        self.capture_device = bool(capture_device)
        self.on_dump = on_dump
        self.watchdog: Watchdog | None = None

    # sink closures (bound methods keep add/remove_sink idempotent)
    def span_sink(self, s) -> None:
        # black-box selectivity: request-root spans and anything ≥1 ms.
        # Micro-spans (per-level expands, lock holds) would displace
        # the interesting history within milliseconds AND bill the hot
        # path (<5% guard); their full fidelity already lives in
        # tracing's own ring, snapshotted into every bundle.
        if s.parent_id and s.dur_us < RING_SPAN_MIN_US:
            return
        self.ring.add("span", {"name": s.name, "trace_id": s.trace_id,
                               "dur_us": s.dur_us, "tid": s.tid})

    def cost_sink(self, rec: dict) -> None:
        self.ring.add("cost", {"shape": rec.get("shape"),
                               "lane": rec.get("lane"),
                               "outcome": rec.get("outcome"),
                               "total_us": rec.get("total_us"),
                               "trace_id": rec.get("trace_id")})


# -- arming -------------------------------------------------------------------

def arm(*, diag_dir: str | None = None, stall_factor: float = STALL_FACTOR,
        stall_floor_ms: float = STALL_FLOOR_MS, poll_s: float = POLL_S,
        grace_s: float = GRACE_S,
        min_dump_interval_s: float = MIN_DUMP_INTERVAL_S,
        maintenance_stall_s: float = MAINT_STALL_S,
        ring_max: int = RING_MAX, alpha=None, pusher=None,
        config: dict | None = None, signals: bool = False,
        capture_device: bool = False, on_dump=None,
        watchdog: bool = True):
    """Arm the flight recorder: subscribe the ring to the span/cost
    streams and (default) start the watchdog daemon. Re-arming
    disarms the previous configuration first. `signals=True` installs
    the SIGUSR2 dump trigger (main thread only; silently skipped
    elsewhere). `on_dump(record, bundle)` observes every dump (bench
    uses it to surface a wedged stage's bundle path)."""
    global _STATE
    if _STATE is not None:
        disarm()
    with _DUMPS_LOCK:  # a fresh arming starts a fresh dump ledger
        del _DUMPS[:]
    st = _State(FlightRing(ring_max), diag_dir, alpha, pusher, config,
                capture_device, on_dump)
    tracing.add_sink(st.span_sink)
    costprofile.add_sink(st.cost_sink)
    _STATE = st
    if watchdog:
        st.watchdog = Watchdog(
            poll_s=poll_s, stall_factor=stall_factor,
            stall_floor_ms=stall_floor_ms, grace_s=grace_s,
            min_dump_interval_s=min_dump_interval_s,
            maintenance_stall_s=maintenance_stall_s, alpha=alpha,
            pusher=pusher).start()
    if signals:
        _install_sigusr2()
    return st


def disarm() -> None:
    """Tear down: unsubscribe sinks, stop the watchdog thread, restore
    the SIGUSR2 handler, forget the registry and dump records."""
    global _STATE
    st = _STATE
    if st is None:
        return
    tracing.remove_sink(st.span_sink)
    costprofile.remove_sink(st.cost_sink)
    if st.watchdog is not None:
        st.watchdog.stop()
    _restore_sigusr2()
    _STATE = None
    with _OPS_LOCK:
        _OPS.clear()
    _RPC_INFLIGHT.clear()
    with _DUMPS_LOCK:
        del _DUMPS[:]


def armed() -> bool:
    return _STATE is not None


def _install_sigusr2() -> None:
    global _PREV_SIG
    import signal

    def handler(_signum, _frame):
        # only mark: the dump runs on the watchdog thread (or a fresh
        # one) — the interrupted frame may hold any lock
        request_dump("sigusr2")

    try:
        _PREV_SIG = signal.signal(signal.SIGUSR2, handler)
    except ValueError:  # not the main thread: no signal trigger
        _PREV_SIG = None


def _restore_sigusr2() -> None:
    global _PREV_SIG
    if _PREV_SIG is None:
        return
    import signal
    with contextlib.suppress(ValueError):
        signal.signal(signal.SIGUSR2, _PREV_SIG)
    _PREV_SIG = None


# -- hook surface (cheap when disarmed) ---------------------------------------

def emit(kind: str, **fields) -> None:
    """Record one subsystem event into the flight ring (admission
    sheds, breaker transitions, maintenance outcomes, corruption/heal
    events). One global load + None check when disarmed."""
    st = _STATE
    if st is not None:
        st.ring.add(kind, fields)


@contextlib.contextmanager
def track(name: str, budget_s: float | None = None,
          predicted_us: float | None = None, lane: str = "",
          ctx=None, query: str | None = None):
    """Register an operation in the in-flight registry for the
    watchdog to walk. `ctx` (a RequestContext) contributes its live
    deadline; `budget_s` sets an explicit one (bench stages). Yields
    the tracked record (None when disarmed)."""
    if _STATE is None:
        yield None
        return
    op = _Tracked()
    op.op_id = next(_IDS)
    op.name = name
    op.lane = lane
    op.predicted_us = (float(predicted_us)
                       if predicted_us is not None else None)
    op.query = " ".join(query.split())[:200] if query else None
    op.trace_id = tracing.current_trace_id()
    op.ident = threading.get_ident()
    op.started = dl.monotonic_s()
    op.budget_deadline = (op.started + budget_s
                          if budget_s is not None else None)
    op.ctx = ctx
    op.convicted = False
    with _OPS_LOCK:
        _OPS[op.op_id] = op
    try:
        yield op
    finally:
        with _OPS_LOCK:
            _OPS.pop(op.op_id, None)


def track_request(ctx, lane: str, predicted_us: float | None = None,
                  query: str | None = None):
    """`Alpha._request`'s registration shell: the request rides its
    RequestContext (live deadline) and its costprior prediction."""
    return track(f"request.{lane}", ctx=ctx, lane=lane,
                 predicted_us=predicted_us, query=query)


@contextlib.contextmanager
def rpc_leg(peer: str, rpc: str):
    """Mark an outbound RPC as in flight on this thread
    (server/task.py Client._attempt wraps every wire attempt): when
    the watchdog convicts a request whose thread is sitting inside a
    leg, the conviction names the wedged PEER — not just the wedged
    request — and the bundle pulls that peer's flight snapshot over
    the DebugFlight RPC. One global load + None check when disarmed."""
    if _STATE is None:
        yield
        return
    ident = threading.get_ident()
    prev = _RPC_INFLIGHT.get(ident)
    _RPC_INFLIGHT[ident] = (peer, rpc, dl.monotonic_s())
    try:
        yield
    finally:
        if prev is None:
            _RPC_INFLIGHT.pop(ident, None)
        else:
            _RPC_INFLIGHT[ident] = prev


def rpc_in_flight(ident: int) -> tuple | None:
    """(peer, rpc, started_mono) of the RPC `ident`'s thread is inside
    right now (None = no outstanding leg)."""
    return _RPC_INFLIGHT.get(ident)


def _peer_leg(op: _Tracked) -> dict:
    leg = _RPC_INFLIGHT.get(op.ident)
    if leg is None:
        return {}
    return {"peer": leg[0], "peer_rpc": leg[1]}


def request_dump(trigger: str) -> None:
    """Queue a dump out-of-band (the SIGUSR2 handler's path). Runs on
    the watchdog thread when armed with one, else on a one-shot
    thread — never on the requesting frame."""
    st = _STATE
    if st is None:
        return
    if st.watchdog is not None:
        st.watchdog.request_dump(trigger)
    else:
        threading.Thread(target=dump, kwargs={"trigger": trigger},
                         daemon=True).start()


# -- the diagnostic bundle ----------------------------------------------------

def _op_evidence(op: _Tracked, now: float) -> dict:
    """One tracked op's full evidence — identity, live stack, and the
    completed spans of its trace. The watchdog pins this at CONVICTION
    time (a short-lived stall may finish before the bundle is built;
    the evidence must survive it); the bundle builder reuses it for
    everything still in flight."""
    d = op.to_dict(now)
    frame = sys._current_frames().get(op.ident)
    if frame is not None:
        d["stack"] = "".join(traceback.format_stack(frame))
    if op.trace_id:
        d["spans"] = [s.to_dict()
                      for s in tracing.trace_spans(op.trace_id)]
    return d


def dump(trigger: str = "manual", reason: dict | None = None,
         alpha=None, write: bool = True) -> dict:
    """Build (and write, when a diag dir is known) one self-contained
    diagnostic bundle. Returns {"path": str|None, "bundle": dict}.
    Works disarmed too (the HTTP surface and `dgraph_tpu diagnose`
    must produce a bundle from ANY live server) — the ring and
    watchdog sections are then empty/absent."""
    st = _STATE
    if alpha is None and st is not None:
        alpha = st.alpha
    bundle = _build_bundle(trigger, reason, alpha, st)
    path = None
    if write and st is not None and st.diag_dir:
        try:
            path = _write_bundle(st.diag_dir, trigger, bundle)
        except OSError:
            xlog.get("flightrec").exception(
                "could not write flight bundle under %s", st.diag_dir)
    METRICS.inc("flight_dumps_total", trigger=trigger)
    record = {"path": path, "trigger": trigger, "t_ms": bundle["t_ms"],
              "reason": reason}
    with _DUMPS_LOCK:
        _DUMPS.append(record)
        del _DUMPS[:-DUMPS_MAX]
    if st is not None and st.on_dump is not None:
        try:
            st.on_dump(record, bundle)
        except Exception:  # noqa: BLE001 — an observer must never fail a dump
            pass
    return {"path": path, "bundle": bundle}


def _build_bundle(trigger: str, reason: dict | None, alpha,
                  st: "_State | None") -> dict:
    now = dl.monotonic_s()
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = {f"{names.get(ident, 'thread')}-{ident}":
              "".join(traceback.format_stack(frame))
              for ident, frame in frames.items()}
    with _OPS_LOCK:
        ops = list(_OPS.values())
    inflight = []
    for op in ops:
        d = _op_evidence(op, now)
        d["thread"] = names.get(op.ident, "thread")
        inflight.append(d)
    bundle = {
        "version": 1,
        "trigger": trigger,
        "reason": reason,
        "t_ms": _now_ms(),
        "stacks": stacks,
        "inflight": inflight,
        "ring": st.ring.recent() if st is not None else [],
        "watchdog": (st.watchdog.state()
                     if st is not None and st.watchdog is not None
                     else {"armed": False}),
        "dumps": dumps(),
        "surfaces": _surfaces(alpha),
        "metrics": METRICS.render(),
        "config": st.config if st is not None else {},
    }
    if reason is not None and reason.get("peer"):
        # peer-correlated diagnostics: the conviction named the peer
        # its stuck RPC leg is wedged on — pull THAT node's in-flight
        # snapshot + flight ring so the bundle answers "wedged on
        # whom" offline (budget-bounded; a dark peer degrades to an
        # error field, never a failed dump)
        bundle["peer_flight"] = _pull_peer_flight(
            alpha, reason["peer"], reason.get("peer_rpc"))
    if st is not None and st.capture_device \
            and trigger.startswith("watchdog"):
        bundle["device_profile"] = _device_capture()
    return bundle


def _pull_peer_flight(alpha, addr: str, rpc: str | None) -> dict:
    """The implicated peer's flight snapshot over the DebugFlight
    worker RPC — through the shared pooled client (breaker-aware) when
    the alpha is clustered, an ad-hoc client otherwise."""
    out: dict = {"addr": addr}
    if rpc:
        out["rpc"] = rpc
    groups = getattr(alpha, "groups", None) if alpha is not None else None
    try:
        with dl.activate(dl.RequestContext(PEER_FLIGHT_BUDGET_MS)):
            if groups is not None:
                out["flight"] = groups.pool(addr).debug_flight()
            else:
                from dgraph_tpu.server.task import Client
                c = Client(addr)
                try:
                    out["flight"] = c.debug_flight()
                finally:
                    c.close()
        METRICS.inc("peer_flight_pulls_total", outcome="ok")
    except Exception as e:  # noqa: BLE001 — a dark peer must not fail the dump
        out["error"] = f"{type(e).__name__}: {e}"[:300]
        METRICS.inc("peer_flight_pulls_total", outcome="error")
    return out


def flight_snapshot(n: int = 256) -> dict:
    """The DebugFlight RPC / `/debug/fleet/flight` document: every
    in-flight op WITH its evidence (stack + trace spans), the threads'
    outstanding RPC legs, the flight ring tail, watchdog state, and
    recent dumps — state()'s peer-correlated twin. Works disarmed
    (ring/watchdog sections then empty), like dump()."""
    now = dl.monotonic_s()
    with _OPS_LOCK:
        ops = list(_OPS.values())
    doc: dict = {
        "armed": _STATE is not None,
        "inflight": [_op_evidence(op, now) for op in ops],
        "rpcs_in_flight": [
            {"thread": ident, "peer": leg[0], "rpc": leg[1],
             "age_s": round(now - leg[2], 3)}
            for ident, leg in list(_RPC_INFLIGHT.items())],
        "dumps": dumps(),
    }
    st = _STATE
    doc["ring"] = st.ring.recent(n) if st is not None else []
    doc["watchdog"] = (st.watchdog.state()
                       if st is not None and st.watchdog is not None
                       else {"armed": False})
    return doc


def _surfaces(alpha) -> dict:
    """Snapshot every debug surface the HTTP layer serves — the bundle
    must answer offline anything `/debug/*` would have answered live."""
    spans = tracing.recent(256)
    out = {
        "traces": [s.to_dict() for s in spans],
        "events": tracing.to_chrome(spans),
        "costs": costprofile.summary(top_n=10),
        "scheduler": costprior.status(top_n=10),
        "locks": locks.GRAPH.snapshot(),
        "races": locks.RACES.snapshot(),
    }
    # memory-governor state (ISSUE 16): an OOM/degrade conviction's
    # bundle must carry the budgets, per-cache residency, and the
    # sticky-degraded shapes that explain it
    from dgraph_tpu.utils import memgov
    out["memory"] = memgov.GOVERNOR.status()
    try:
        from dgraph_tpu.server.http import slow_queries_snapshot
        out["slow_queries"] = slow_queries_snapshot()
    except Exception:  # noqa: BLE001 — surface optional outside a server
        out["slow_queries"] = []
    adm = getattr(alpha, "admission", None) if alpha is not None else None
    out["admission"] = ({"enabled": True, **adm.status()}
                        if adm is not None else {"enabled": False})
    groups = getattr(alpha, "groups", None) if alpha is not None else None
    res = getattr(groups, "resilience", None) if groups is not None \
        else None
    out["peers"] = ({"enabled": True, "peers": res.snapshot()}
                    if res is not None else {"enabled": False})
    # retained history (ISSUE 17): the last minutes LEADING UP TO this
    # dump — per-series rates and latency percentiles plus SLO states,
    # so a conviction bundle shows the approach, not just the crash
    try:
        from dgraph_tpu.utils import timeseries
        out["timeseries"] = timeseries.recent_window(300.0)
    except Exception:  # noqa: BLE001 — surface optional when disarmed
        out["timeseries"] = None
    return out


def _device_capture(capture_s: float = 0.25) -> dict:
    """Optional single-flight jax.profiler capture riding the PR-8
    machinery — `tracing.profile_start`'s lock guarantees never two
    concurrent; a capture already running reports the conflict instead
    of corrupting it."""
    try:
        d = tracing.profile_start()
        time.sleep(capture_s)
        return {"dir": tracing.profile_stop()}
    except (RuntimeError, ValueError) as e:
        return {"error": str(e)}
    except Exception as e:  # noqa: BLE001 — profiling must never fail a dump
        return {"error": f"{type(e).__name__}: {e}"}


_DUMP_SEQ = itertools.count(1)


def _write_bundle(diag_dir: str, trigger: str, bundle: dict) -> str:
    from dgraph_tpu.store import vault
    os.makedirs(diag_dir, exist_ok=True)
    safe = "".join(c if c.isalnum() else "-" for c in trigger)
    path = os.path.join(
        diag_dir,
        f"flight-{safe}-{bundle['t_ms']}-{next(_DUMP_SEQ)}.json")
    vault.atomic_write(path,
                       json.dumps(bundle, default=str).encode())
    return path


# -- surfacing ---------------------------------------------------------------

def state(n: int = 100) -> dict:
    """The `GET /debug/flightrecorder` document: ring tail + watchdog
    state + recent dumps + in-flight count."""
    st = _STATE
    doc: dict = {"armed": st is not None, "dumps": dumps()}
    with _OPS_LOCK:
        doc["inflight"] = len(_OPS)
    if st is not None:
        doc["diag_dir"] = st.diag_dir
        doc["ring"] = st.ring.recent(n)
        doc["ring_stats"] = st.ring.stats()
        doc["watchdog"] = (st.watchdog.state()
                           if st.watchdog is not None
                           else {"armed": False})
    return doc


def dumps() -> list[dict]:
    """Recent dump records (newest last)."""
    with _DUMPS_LOCK:
        return [dict(d) for d in _DUMPS]
