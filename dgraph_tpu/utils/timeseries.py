"""Retained metrics history: the sampler daemon + windowed-point ring.

Every /debug surface before this PR is a point-in-time snapshot; the
run-up to a regression — the climbing p99, the creeping shed rate, the
arrival spike before a watchdog conviction — was invisible unless
someone was watching. This module retains it: a daemon thread samples
the shared metrics `Registry` every `interval_s` into a bounded ring
of WINDOWED points — counters become rates (delta/dt), gauges become
values, histograms become per-window bucket deltas with interpolated
p50/p90/p99 — and serves windows of that history to `/debug/timeseries`,
the SLO engine's burn-rate evaluation (utils/slo.py), flight bundles
(the "timeseries" surface: the approach, not just the crash), the fleet
merge, and the Holt-trend load forecast that feeds admission's
predicted-load shedding.

The ring is a governed cache: it registers as `timeseries.ring` with
the memory governor (host kind), so under budget pressure the OLDEST
history is surrendered first (`ts_ring_dropped_total` counts both
bound- and governor-drops). Timestamps are monotonic; consumers see
`age_s`, never wall clock.

Off-path contract (the PR-9 pattern): an unarmed process pays one
module-global load + None check at the admission probe and nothing on
the query path — the sampler reads the registry from its own thread.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from dgraph_tpu.utils import locks
from dgraph_tpu.utils.metrics import METRICS

__all__ = ["Ring", "Window", "Forecast", "Sampler", "arm", "disarm",
           "state", "status", "recent_window", "forecast_probe",
           "DEFAULT_INTERVAL_S", "DEFAULT_RING_POINTS"]

DEFAULT_INTERVAL_S = 1.0
DEFAULT_RING_POINTS = 3600        # 1h of history at the default cadence

# rough per-entry byte estimate for the governor's accounting: budgets
# need relative truth, not an audit (memgov.estimate_nbytes is too slow
# to run per tick)
_ENTRY_BYTES = 48
_POINT_BYTES = 160

# Holt (double-exponential) trend smoothing for the arrival-rate
# forecast, and the demand margin past which admission sheds ahead of
# the queue filling (Little's law: demand = rate × cost)
_HOLT_ALPHA = 0.5
_HOLT_BETA = 0.3
_FORECAST_HORIZON_S = 30.0
_FORECAST_MARGIN = 2.0


def _percentile(buckets: tuple, counts: list, n: float, q: float) -> float:
    """Deterministic bucket-interpolated percentile: rank q·n located in
    the cumulative counts, linearly interpolated inside its bucket. The
    +Inf slot clamps to the top finite bound (no invented tail)."""
    if n <= 0:
        return 0.0
    rank = q * n
    acc = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        lo = float(buckets[i - 1]) if i > 0 else 0.0
        hi = float(buckets[i]) if i < len(buckets) else float(buckets[-1])
        if acc + c >= rank:
            frac = min(max((rank - acc) / c, 0.0), 1.0)
            return lo + (hi - lo) * frac
        acc += c
    return float(buckets[-1])


class Window:
    """A slice of ring points covering the last `seconds` — the view
    the SLO evaluators and debug endpoints aggregate over."""

    def __init__(self, points: list, span_s: float):
        self.points = points
        self.span_s = span_s

    def delta(self, prefix: str) -> float:
        """Summed counter increments across series matching `prefix`
        (rendered-name prefix: `shed_total` matches every label set)."""
        total = 0.0
        for p in self.points:
            for name, d in p["deltas"].items():
                if name.startswith(prefix):
                    total += d
        return total

    def rate(self, prefix: str) -> float:
        return self.delta(prefix) / self.span_s if self.span_s else 0.0

    def hist(self, prefix: str) -> dict:
        """Merged windowed histogram across matching series: summed
        bucket deltas + n + sum over the window."""
        buckets: tuple = ()
        counts: list = []
        n = 0.0
        total = 0.0
        for p in self.points:
            for name, h in p["hists"].items():
                if not name.startswith(prefix):
                    continue
                if not counts:
                    buckets = h["buckets"]
                    counts = [0.0] * len(h["counts"])
                for i, c in enumerate(h["counts"]):
                    counts[i] += c
                n += h["n"]
                total += h["sum"]
        return {"buckets": buckets, "counts": counts, "n": n,
                "sum": total}

    def hist_n(self, prefix: str) -> float:
        return self.hist(prefix)["n"]

    def frac_above(self, prefix: str, threshold: float):
        """(bad, total): windowed observations whose bucket's upper
        bound exceeds `threshold` — the latency-SLO bad-event count.
        Conservative at bucket granularity, deterministic always."""
        h = self.hist(prefix)
        bad = 0.0
        for i, c in enumerate(h["counts"]):
            hi = (float(h["buckets"][i]) if i < len(h["buckets"])
                  else float("inf"))
            if hi > threshold:
                bad += c
        return bad, h["n"]

    def percentile(self, prefix: str, q: float) -> float:
        h = self.hist(prefix)
        return _percentile(h["buckets"], h["counts"], h["n"], q)


class Ring:
    """The bounded, governed point ring. `sample()` diffs the registry
    against the previous snapshot; everything derived (rates, windowed
    percentiles) is computed once at sample time so reads are cheap."""

    def __init__(self, points: int = DEFAULT_RING_POINTS,
                 registry=METRICS):
        self.capacity = max(2, int(points))
        self.registry = registry
        self._lock = locks.make_lock("timeseries.ring")
        self._points: deque = deque()
        self._prev_counters: dict = {}
        self._prev_hists: dict = {}
        self._prev_t: float | None = None
        self._bytes = 0
        self.points_total = 0
        self.dropped_total = 0
        locks.guarded(self, "timeseries.ring")
        from dgraph_tpu.utils import memgov
        self._gov_id = memgov.GOVERNOR.register(
            "timeseries.ring", "host", self._resident_bytes,
            self._evict_one, owner=self)

    # -- governor callbacks ----------------------------------------------

    def _resident_bytes(self) -> int:
        return self._bytes

    def _evict_one(self) -> int:
        """Surrender the oldest 1/16th of retained history (at least
        one point) — the governor's unit of progress."""
        with self._lock:
            k = min(len(self._points), max(1, self.capacity // 16))
            freed = 0
            for _ in range(k):
                freed += self._pop_oldest_locked()
        if k:
            METRICS.inc("ts_ring_dropped_total", value=float(k),
                        reason="memgov")
        return freed

    def _pop_oldest_locked(self) -> int:
        p = self._points.popleft()
        b = p["_bytes"]
        self._bytes -= b
        self.dropped_total += 1
        return b

    # -- sampling ---------------------------------------------------------

    def sample(self, now: float | None = None) -> dict | None:
        """Take one windowed point (the sampler tick; tests call it
        directly with explicit `now` for determinism). The first call
        baselines and retains nothing — a delta needs two snapshots."""
        snap = self.registry.snapshot()
        hists = self.registry.hist_snapshot()
        t = time.monotonic() if now is None else float(now)
        with self._lock:
            prev_c, prev_h = self._prev_counters, self._prev_hists
            first = self._prev_t is None
            dt = 0.0 if first else max(t - self._prev_t, 1e-9)
            self._prev_counters = snap["counters"]
            self._prev_hists = hists
            self._prev_t = t
            if first:
                return None
            deltas, rates = {}, {}
            for name, v in snap["counters"].items():
                d = v - prev_c.get(name, 0.0)
                if d:
                    deltas[name] = d
                    rates[name] = d / dt
            hp = {}
            for name, h in hists.items():
                ph = prev_h.get(name)
                pc = ph["counts"] if ph else [0] * len(h["counts"])
                dcounts = [c - p for c, p in zip(h["counts"], pc)]
                dn = h["n"] - (ph["n"] if ph else 0)
                if dn <= 0:
                    continue
                bks = h["buckets"]
                hp[name] = {
                    "buckets": bks, "counts": dcounts, "n": dn,
                    "sum": h["sum"] - (ph["sum"] if ph else 0.0),
                    "p50": _percentile(bks, dcounts, dn, 0.50),
                    "p90": _percentile(bks, dcounts, dn, 0.90),
                    "p99": _percentile(bks, dcounts, dn, 0.99)}
            nbytes = (_POINT_BYTES
                      + _ENTRY_BYTES * (len(deltas) * 2
                                        + len(snap["gauges"]))
                      + sum(_ENTRY_BYTES + 8 * len(h["counts"])
                            for h in hp.values()))
            point = {"t": t, "dt": dt, "deltas": deltas, "rates": rates,
                     "gauges": dict(snap["gauges"]), "hists": hp,
                     "_bytes": nbytes}
            bound_drops = 0
            while len(self._points) >= self.capacity:
                self._pop_oldest_locked()
                bound_drops += 1
            self._points.append(point)
            self._bytes += nbytes
            self.points_total += 1
        METRICS.inc("ts_points_total")
        if bound_drops:
            METRICS.inc("ts_ring_dropped_total",
                        value=float(bound_drops), reason="bound")
        from dgraph_tpu.utils import memgov
        memgov.GOVERNOR.maybe_evict("host")
        return point

    # -- reads ------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._points)

    def window(self, seconds: float, now: float | None = None) -> Window:
        with self._lock:
            if not self._points:
                return Window([], 0.0)
            end = self._points[-1]["t"] if now is None else float(now)
            lo = end - float(seconds)
            pts = [p for p in self._points if p["t"] > lo]
            span = (pts[-1]["t"] - pts[0]["t"] + pts[0]["dt"]
                    if pts else 0.0)
            return Window(pts, span)

    def series_names(self) -> dict:
        """Available series by kind, from the newest point."""
        with self._lock:
            if not self._points:
                return {"rates": [], "gauges": [], "hists": []}
            p = self._points[-1]
            return {"rates": sorted(p["rates"]),
                    "gauges": sorted(p["gauges"]),
                    "hists": sorted(p["hists"])}

    def series(self, name: str, window_s: float | None = None,
               rate: bool = True, now: float | None = None) -> dict:
        """Point list for every series matching `name` (prefix) —
        the ?name= view of /debug/timeseries. Counter series serve
        rates (or raw deltas with rate=false); histograms serve the
        windowed percentiles; gauges serve values."""
        with self._lock:
            pts = list(self._points)
        if not pts:
            return {"series": {}, "points": 0}
        end = pts[-1]["t"] if now is None else float(now)
        if window_s:
            pts = [p for p in pts if p["t"] > end - float(window_s)]
        out: dict[str, list] = {}
        for p in pts:
            age = round(end - p["t"], 3)
            table = p["rates"] if rate else p["deltas"]
            for sname, v in table.items():
                if sname.startswith(name):
                    out.setdefault(sname, []).append(
                        {"age_s": age, "value": round(v, 6)})
            for sname, v in p["gauges"].items():
                if sname.startswith(name):
                    out.setdefault(sname, []).append(
                        {"age_s": age, "value": v})
            for sname, h in p["hists"].items():
                if sname.startswith(name):
                    out.setdefault(sname, []).append(
                        {"age_s": age, "n": h["n"],
                         "p50": round(h["p50"], 1),
                         "p90": round(h["p90"], 1),
                         "p99": round(h["p99"], 1)})
        return {"series": out, "points": len(pts)}

    def summary(self, window_s: float = 60.0) -> dict:
        """Compact recent-history digest: ring occupancy + the last
        window's top rates and latency percentiles — what bench stages
        and the fleet merge carry."""
        w = self.window(window_s)
        rates: dict[str, float] = {}
        for p in w.points:
            for name, d in p["deltas"].items():
                rates[name] = rates.get(name, 0.0) + d
        span = w.span_s or 1.0
        top = {k: round(v / span, 3) for k, v in
               sorted(rates.items(), key=lambda kv: -kv[1])[:8]}
        lat = w.hist("query_latency_us")
        return {"points": len(self), "points_total": self.points_total,
                "dropped_total": self.dropped_total,
                "resident_bytes": self._bytes,
                "window_s": round(span, 3),
                "top_rates": top,
                "query_latency": {
                    "n": lat["n"],
                    "p50_us": round(_percentile(
                        lat["buckets"], lat["counts"], lat["n"], 0.5), 1),
                    "p99_us": round(_percentile(
                        lat["buckets"], lat["counts"], lat["n"], 0.99), 1),
                } if lat["n"] else None}


class Forecast:
    """Holt double-exponential trend over per-lane arrival rates; the
    admission probe sheds when predicted demand (forecast arrivals/s ×
    predicted cost, Little's law) exceeds `margin` × the lane's
    tokens. Deterministic given the update sequence."""

    def __init__(self, alpha: float = _HOLT_ALPHA,
                 beta: float = _HOLT_BETA,
                 horizon_s: float = _FORECAST_HORIZON_S,
                 margin: float = _FORECAST_MARGIN):
        self.alpha = alpha
        self.beta = beta
        self.horizon_s = horizon_s
        self.margin = margin
        self._lock = locks.make_lock("timeseries.forecast")
        self._level: dict[str, float] = {}
        self._trend: dict[str, float] = {}
        self.sheds = 0
        locks.guarded(self, "timeseries.forecast")

    def update(self, lane: str, rate: float, dt: float = 1.0) -> None:
        """One sampled arrival rate (requests/s) for `lane`; trend is
        kept in per-second units so the horizon is cadence-free."""
        with self._lock:
            if lane not in self._level:
                self._level[lane] = rate
                self._trend[lane] = 0.0
                return
            prev = self._level[lane]
            level = (self.alpha * rate
                     + (1.0 - self.alpha) * (prev + self._trend[lane] * dt))
            self._trend[lane] = (self.beta * (level - prev) / max(dt, 1e-9)
                                 + (1.0 - self.beta) * self._trend[lane])
            self._level[lane] = level

    def predicted_rate(self, lane: str) -> float:
        with self._lock:
            if lane not in self._level:
                return 0.0
            return max(0.0, self._level[lane]
                       + self._trend[lane] * self.horizon_s)

    def predicted_demand(self, lane: str, cost_us: float) -> float:
        """Expected concurrent requests at the horizon: λ × W."""
        return self.predicted_rate(lane) * max(cost_us, 0.0) / 1e6

    def should_shed(self, lane: str, cost_us: float | None,
                    max_inflight: int) -> bool:
        """True when admitting more of this lane's arrivals is
        predicted to exceed `margin` × its tokens before the horizon —
        shed NOW, while the hint is still short, instead of after the
        queue fills. Requests with no predicted cost fall back to the
        lane's prior EMA; no signal at all never sheds."""
        cost = cost_us
        if cost is None:
            try:
                from dgraph_tpu.utils import costprior
                cost = costprior.lane_ema_us(lane)
            except Exception:
                cost = None
        if not cost:
            return False
        demand = self.predicted_demand(lane, cost)
        if demand <= self.margin * max(max_inflight, 1):
            return False
        with self._lock:
            self.sheds += 1
        return True

    def status(self) -> dict:
        with self._lock:
            return {"lanes": {lane: {
                        "level": round(self._level[lane], 4),
                        "trend_per_s": round(self._trend[lane], 6),
                    } for lane in sorted(self._level)},
                    "horizon_s": self.horizon_s,
                    "margin": self.margin,
                    "sheds": self.sheds}


class Sampler:
    """The daemon: one tick per `interval_s` — sample the ring, update
    the forecast from the lane arrival counters, evaluate the SLO
    engine. Mirrors the flight watchdog's loop discipline (daemon
    thread, Event stop, exception-swallowing tick)."""

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S,
                 ring: Ring | None = None, engine=None,
                 forecast: Forecast | None = None, registry=METRICS):
        self.interval_s = max(float(interval_s), 0.01)
        self.ring = ring if ring is not None else Ring(registry=registry)
        self.engine = engine
        self.forecast = forecast
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def tick(self, now: float | None = None) -> dict | None:
        point = self.ring.sample(now=now)
        if point is not None and self.forecast is not None:
            for lane in ("read", "mutate"):
                series = f'admission_requests_total{{lane="{lane}"}}'
                self.forecast.update(lane,
                                     point["rates"].get(series, 0.0),
                                     dt=point["dt"])
        if self.engine is not None:
            self.engine.evaluate(self.ring, now=now)
        return point

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                from dgraph_tpu.utils import logging as xlog
                xlog.get("timeseries").exception("sampler tick failed")

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="ts-sampler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    def status(self) -> dict:
        doc = {"interval_s": self.interval_s,
               "running": self._thread is not None,
               "ring": self.ring.summary()}
        if self.forecast is not None:
            doc["forecast"] = self.forecast.status()
        return doc


# the armed sampler + forecaster (None = disarmed). The admission
# probe reads `_FORECAST` with one global load + None check — the
# off-path cost when forecast shedding is disabled.
_STATE: Sampler | None = None
_FORECAST: Forecast | None = None


def arm(*, interval_s: float = DEFAULT_INTERVAL_S,
        ring_points: int = DEFAULT_RING_POINTS, slo_engine=None,
        forecast: bool = True, registry=METRICS,
        start_thread: bool = True) -> Sampler:
    """Arm the sampler (idempotent: re-arming replaces). `slo_engine`
    also installs as slo.ENGINE so the watchdog and /debug/slo see it;
    `forecast=False` leaves the admission off-path bit-identical."""
    global _STATE, _FORECAST
    disarm()
    fc = Forecast() if forecast else None
    s = Sampler(interval_s=interval_s,
                ring=Ring(points=ring_points, registry=registry),
                engine=slo_engine, forecast=fc, registry=registry)
    if slo_engine is not None:
        from dgraph_tpu.utils import slo as _slo
        _slo.install(slo_engine)
    _STATE = s
    _FORECAST = fc
    if start_thread:
        s.start()
    return s


def disarm() -> None:
    global _STATE, _FORECAST
    s = _STATE
    _STATE = None
    _FORECAST = None
    if s is not None:
        s.stop()
        if s.engine is not None:
            from dgraph_tpu.utils import slo as _slo
            if _slo.ENGINE is s.engine:
                _slo.uninstall()


def state() -> Sampler | None:
    return _STATE


def forecast_probe(lane: str, cost_us: float | None,
                   max_inflight: int) -> bool:
    """The admission fast probe: disarmed processes pay one global
    load + None check (the PR-9 off-path contract)."""
    fc = _FORECAST
    if fc is None:
        return False
    return fc.should_shed(lane, cost_us, max_inflight)


def status(name: str | None = None, window_s: float | None = None,
           rate: bool = True) -> dict:
    """The /debug/timeseries document."""
    s = _STATE
    if s is None:
        return {"armed": False}
    doc = {"armed": True, **s.status()}
    if name:
        doc.update(s.ring.series(name, window_s=window_s, rate=rate))
    else:
        doc["names"] = s.ring.series_names()
    return doc


def recent_window(seconds: float = 300.0) -> dict | None:
    """The flight-bundle "timeseries" surface: the last `seconds` of
    retained history leading up to the dump — per-series rates and
    latency percentiles, newest last."""
    s = _STATE
    if s is None or not len(s.ring):
        return None
    w = s.ring.window(seconds)
    end = w.points[-1]["t"] if w.points else 0.0
    pts = []
    for p in w.points:
        pts.append({
            "age_s": round(end - p["t"], 3),
            "rates": {k: round(v, 4) for k, v in p["rates"].items()},
            "gauges": p["gauges"],
            "hists": {k: {"n": h["n"], "p50": round(h["p50"], 1),
                          "p90": round(h["p90"], 1),
                          "p99": round(h["p99"], 1)}
                      for k, h in p["hists"].items()}})
    doc = {"window_s": round(w.span_s, 3), "points": pts,
           "summary": s.ring.summary(seconds)}
    if s.engine is not None:
        doc["slo"] = s.engine.status()["states"]
    return doc
