"""Host-side jit compile-cache accounting.

XLA retraces/recompiles whenever a kernel launch's STATIC configuration
(bucketed shapes, caps, depth) changes; the r5 bench stall showed that a
wedged chip and a multi-second compile are indistinguishable without
telemetry. Each kernel call site wraps its launch in `jit_call(kernel,
key)` where `key` is exactly the static tuple that forces a distinct
program — first sight of a key counts as a compile (timed: the first
invocation traces + compiles synchronously before dispatch), repeats
count as cache hits.

The timing is an upper bound on compile cost (it includes the first
dispatch), which is the honest observable without reaching into XLA
internals; steady-state calls are classified exactly.
"""

from __future__ import annotations

import contextlib
import time

from dgraph_tpu.utils import locks, tracing
from dgraph_tpu.utils.metrics import METRICS

_seen: set = set()
_lock = locks.make_lock("jitcache.seen")

# compile times ladder: 10ms … 100s in µs
COMPILE_BUCKETS_US = (10_000, 100_000, 500_000, 1_000_000, 5_000_000,
                      10_000_000, 100_000_000)


def seen(kernel: str, key: tuple) -> bool:
    with _lock:
        return (kernel, key) in _seen


@contextlib.contextmanager
def jit_call(kernel: str, key: tuple):
    """Wrap one jitted-kernel launch; classifies it as compile (first
    time this static key is seen) or cache hit, and feeds the shared
    metrics/tracing registries. Yields True when a compile is expected.

    Every `jit_call` site is exactly one device dispatch, so this is
    ALSO where per-request launch accounting lives: the wrapped span
    feeds `costprofile.note_launch` — `kernel_launches` counts one per
    site reached, and the host-side gap since the previous launch in
    the same recorder frame lands in `launch_gap_us` (the dispatch-
    overhead baseline the whole-query fused path collapses to a single
    launch)."""
    from dgraph_tpu.utils import costprofile
    with _lock:
        new = (kernel, key) not in _seen
        if new:
            _seen.add((kernel, key))
    t0 = time.perf_counter()
    try:
        if not new:
            METRICS.inc("jit_cache_hits_total", kernel=kernel)
            costprofile.add("jit_cache_hits", 1)
            yield False
            return
        METRICS.inc("jit_compile_total", kernel=kernel)
        with tracing.span("jit.compile", kernel=kernel, key=str(key)):
            try:
                yield True
            finally:
                compile_us = (time.perf_counter() - t0) * 1e6
                METRICS.observe("jit_compile_us", compile_us,
                                buckets=COMPILE_BUCKETS_US, kernel=kernel)
                # per-kernel-family compile cost joins the request's
                # cost record (the compile-vs-execute split the cost
                # model needs)
                costprofile.add_kernel(kernel, compile_us=compile_us)
    finally:
        costprofile.note_launch(t0, time.perf_counter())


def reset() -> None:
    """Test hook: forget every key (a fresh process compiles anew)."""
    with _lock:
        _seen.clear()


class Memo:
    """Bounded LRU memo for host-side derived objects that amortize like
    compiled programs do (batch PLANS keyed by query shape, the bench's
    ELL build) — the host-side sibling of the jit compile cache above.
    Callers classify hits/misses into their own metrics; the memo only
    stores. Thread-safe via a named lock so the lock-order sanitizer
    covers every cache the batch path grew in PR 7."""

    def __init__(self, name: str, capacity: int = 128):
        import collections
        self.name = name
        self.capacity = capacity
        self._d: "collections.OrderedDict" = collections.OrderedDict()
        self._lock = locks.make_lock(f"jitcache.memo.{name}")
        locks.guarded(self, "jitcache.memo.*")

    def get(self, key):
        with self._lock:
            if key not in self._d:
                return None
            self._d.move_to_end(key)
            return self._d[key]

    def put(self, key, value) -> None:
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._d.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)
