"""Host-side jit compile-cache accounting.

XLA retraces/recompiles whenever a kernel launch's STATIC configuration
(bucketed shapes, caps, depth) changes; the r5 bench stall showed that a
wedged chip and a multi-second compile are indistinguishable without
telemetry. Each kernel call site wraps its launch in `jit_call(kernel,
key)` where `key` is exactly the static tuple that forces a distinct
program — first sight of a key counts as a compile (timed: the first
invocation traces + compiles synchronously before dispatch), repeats
count as cache hits.

The timing is an upper bound on compile cost (it includes the first
dispatch), which is the honest observable without reaching into XLA
internals; steady-state calls are classified exactly.
"""

from __future__ import annotations

import contextlib
import time

from dgraph_tpu.utils import locks, tracing
from dgraph_tpu.utils.metrics import METRICS

_seen: set = set()
_lock = locks.make_lock("jitcache.seen")

# compile times ladder: 10ms … 100s in µs
COMPILE_BUCKETS_US = (10_000, 100_000, 500_000, 1_000_000, 5_000_000,
                      10_000_000, 100_000_000)


def seen(kernel: str, key: tuple) -> bool:
    with _lock:
        return (kernel, key) in _seen


@contextlib.contextmanager
def jit_call(kernel: str, key: tuple):
    """Wrap one jitted-kernel launch; classifies it as compile (first
    time this static key is seen) or cache hit, and feeds the shared
    metrics/tracing registries. Yields True when a compile is expected.

    Every `jit_call` site is exactly one device dispatch, so this is
    ALSO where per-request launch accounting lives: the wrapped span
    feeds `costprofile.note_launch` — `kernel_launches` counts one per
    site reached, and the host-side gap since the previous launch in
    the same recorder frame lands in `launch_gap_us` (the dispatch-
    overhead baseline the whole-query fused path collapses to a single
    launch)."""
    from dgraph_tpu.utils import costprofile
    with _lock:
        new = (kernel, key) not in _seen
        if new:
            _seen.add((kernel, key))
    t0 = time.perf_counter()
    try:
        if not new:
            METRICS.inc("jit_cache_hits_total", kernel=kernel)
            costprofile.add("jit_cache_hits", 1)
            yield False
            return
        METRICS.inc("jit_compile_total", kernel=kernel)
        with tracing.span("jit.compile", kernel=kernel, key=str(key)):
            try:
                yield True
            finally:
                compile_us = (time.perf_counter() - t0) * 1e6
                METRICS.observe("jit_compile_us", compile_us,
                                buckets=COMPILE_BUCKETS_US, kernel=kernel)
                # per-kernel-family compile cost joins the request's
                # cost record (the compile-vs-execute split the cost
                # model needs)
                costprofile.add_kernel(kernel, compile_us=compile_us)
    finally:
        costprofile.note_launch(t0, time.perf_counter())


def reset() -> None:
    """Test hook: forget every key (a fresh process compiles anew)."""
    with _lock:
        _seen.clear()


class Memo:
    """Bounded LRU memo for host-side derived objects that amortize like
    compiled programs do (batch PLANS keyed by query shape, the bench's
    ELL build) — the host-side sibling of the jit compile cache above.
    Callers classify hits/misses into their own metrics; the memo only
    stores. Thread-safe via a named lock so the lock-order sanitizer
    covers every cache the batch path grew in PR 7.

    `governed=` names the memory-governor cache this memo registers as
    (graftlint R14 requires every Memo to pick one or waive): the memo
    then accounts bytes per entry (`put(..., nbytes=, rebuild_us=)`) and
    surrenders its LRU-coldest entry on demand, priced at rebuild-µs per
    byte for the governor's cross-cache eviction ordering."""

    def __init__(self, name: str, capacity: int = 128,
                 governed: str | None = None, kind: str = "host"):
        import collections
        self.name = name
        self.capacity = capacity
        self._d: "collections.OrderedDict" = collections.OrderedDict()
        self._sizes: dict = {}
        self._costs: dict = {}
        self._bytes = 0
        self._lock = locks.make_lock(f"jitcache.memo.{name}")
        locks.guarded(self, "jitcache.memo.*")
        if governed is not None:
            from dgraph_tpu.utils import memgov
            memgov.GOVERNOR.register(governed, kind, self.nbytes,
                                     self.evict_one,
                                     value_cb=self.coldest_value,
                                     owner=self)

    def get(self, key):
        with self._lock:
            if key not in self._d:
                return None
            self._d.move_to_end(key)
            return self._d[key]

    def put(self, key, value, nbytes: int | None = None,
            rebuild_us: float | None = None) -> None:
        """Insert (LRU-newest). `nbytes` is the entry's resident size
        (estimated when omitted) and `rebuild_us` what recomputing it
        costs — the governor evicts low rebuild-value-per-byte first."""
        if nbytes is None:
            from dgraph_tpu.utils import memgov
            nbytes = memgov.estimate_nbytes(value)
        with self._lock:
            self._drop_locked(key)
            self._d[key] = value
            self._sizes[key] = int(nbytes)
            if rebuild_us is not None:
                self._costs[key] = float(rebuild_us)
            self._bytes += int(nbytes)
            while len(self._d) > self.capacity:
                k, _ = self._d.popitem(last=False)
                self._bytes -= self._sizes.pop(k, 0)
                self._costs.pop(k, None)

    def _drop_locked(self, key) -> None:
        if key in self._d:
            del self._d[key]
            self._bytes -= self._sizes.pop(key, 0)
            self._costs.pop(key, None)

    def reprice(self, key, rebuild_us: float) -> None:
        """Update an entry's rebuild cost after the fact (fused programs
        only learn their true compile µs at first dispatch)."""
        with self._lock:
            if key in self._d:
                self._costs[key] = float(rebuild_us)

    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    def evict_one(self) -> int:
        """Drop the LRU-coldest entry; returns bytes freed (0 = empty)."""
        with self._lock:
            if not self._d:
                return 0
            k, _ = self._d.popitem(last=False)
            freed = self._sizes.pop(k, 0)
            self._costs.pop(k, None)
            self._bytes -= freed
            return freed

    def coldest_value(self) -> float | None:
        """Recompute-µs-per-byte of the entry evict_one would drop."""
        with self._lock:
            if not self._d:
                return None
            k = next(iter(self._d))
            cost = self._costs.get(k)
            if cost is None:
                return None
            return cost / max(self._sizes.get(k, 1), 1)

    def clear(self) -> None:
        with self._lock:
            self._d.clear()
            self._sizes.clear()
            self._costs.clear()
            self._bytes = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)
