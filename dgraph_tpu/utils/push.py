"""Live telemetry push: spans + cost records → an OTLP collector.

ROADMAP carried "live span push to a collector (export is
shutdown/pull-shaped today)" since PR 4 — `--trace_export` writes
OTLP/JSON at shutdown and `/debug/traces` serves pulls, but nothing
STREAMS, so the chip window's telemetry is only attributable
post-mortem. This closes it: a `TelemetryPusher` subscribes to the
span registry (tracing.add_sink) and the cost-record stream
(costprofile.add_sink), buffers bounded, and a background thread POSTs
batches to the collector:

  * spans      → `<url>/v1/traces` as OTLP/JSON (`tracing.to_otlp`)
  * cost recs  → `<url>/v1/costs`  as `{"records": [...]}` JSON

Contracts (tested in tests/test_costprofile.py):
  * NEVER blocks the request path: the sink appends under a lock; a
    full buffer drops the OLDEST entry and counts
    `telemetry_dropped_total{kind=}` — an explicit drop counter, not a
    silent deque overflow.
  * retry with backoff: a failed POST re-queues its batch at the front
    (oldest-first order preserved), doubles the delay (jittered cap),
    and counts `telemetry_push_total{outcome="error"}`; successes
    count `outcome="ok"`.
  * graceful no-op when unconfigured: the CLI only constructs a pusher
    when `--telemetry_push_url` is set.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

from dgraph_tpu.utils import costprofile, locks, tracing
from dgraph_tpu.utils.metrics import METRICS

__all__ = ["TelemetryPusher"]

_BACKOFF_BASE_S = 0.5
_BACKOFF_CAP_S = 30.0


class TelemetryPusher:
    """Background exporter thread with a bounded two-stream buffer."""

    def __init__(self, url: str, interval_s: float = 5.0,
                 buffer_max: int = 2048, batch_max: int = 256,
                 timeout_s: float = 2.0):
        self.url = url.rstrip("/")
        self.interval_s = max(float(interval_s), 0.05)
        self.buffer_max = int(buffer_max)
        self.batch_max = int(batch_max)
        self.timeout_s = float(timeout_s)
        self._spans: list = []
        self._costs: list = []
        self._lock = locks.make_lock("push.buffer")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._backoff_s = 0.0
        # exporter-loop liveness for the flight-recorder watchdog: the
        # loop stamps this every cycle; a stale stamp with a non-empty
        # buffer means the pusher wedged (utils/flightrec.py)
        self._last_cycle_mono = time.monotonic()
        locks.guarded(self, "push.buffer")

    # -- request-path sinks (must stay cheap + non-blocking) -----------------
    def _offer(self, buf: list, kind: str, item) -> None:
        with self._lock:
            if len(buf) >= self.buffer_max:
                del buf[0]
                METRICS.inc("telemetry_dropped_total", kind=kind)
            buf.append(item)

    def offer_span(self, span) -> None:
        self._offer(self._spans, "span", span)

    def offer_cost(self, record: dict) -> None:
        self._offer(self._costs, "cost", record)

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "TelemetryPusher":
        tracing.add_sink(self.offer_span)
        costprofile.add_sink(self.offer_cost)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="telemetry-push")
        self._thread.start()
        return self

    def stop(self, flush: bool = True) -> None:
        """Unsubscribe and stop; `flush=True` attempts one final push
        of whatever is buffered (best effort — shutdown never hangs on
        a dead collector beyond one POST timeout per stream)."""
        tracing.remove_sink(self.offer_span)
        costprofile.remove_sink(self.offer_cost)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout_s * 3)
        if flush:
            self._push_once()

    # -- exporter loop --------------------------------------------------------
    def _run(self) -> None:
        while True:
            # backoff is written by this thread on push failure and
            # read by status() on HTTP threads: all accesses ride the
            # buffer lock (ISSUE-12 audit — the pusher-bookkeeping race)
            with self._lock:
                delay = self._backoff_s or self.interval_s
                self._last_cycle_mono = time.monotonic()
            if self._stop.wait(delay):
                return
            self._push_once()
            with self._lock:
                self._last_cycle_mono = time.monotonic()

    def _take(self) -> tuple[list, list]:
        with self._lock:
            spans = self._spans[: self.batch_max]
            del self._spans[: len(spans)]
            costs = self._costs[: self.batch_max]
            del self._costs[: len(costs)]
        return spans, costs

    def _requeue(self, buf: list, kind: str, batch: list) -> None:
        """Put a failed batch back at the FRONT (order preserved);
        entries that no longer fit drop, counted."""
        with self._lock:
            room = self.buffer_max - len(buf)
            if room < len(batch):
                METRICS.inc("telemetry_dropped_total",
                            float(len(batch) - max(room, 0)), kind=kind)
                batch = batch[len(batch) - max(room, 0):]
            buf[:0] = batch

    def _push_once(self) -> None:
        spans, costs = self._take()
        if not spans and not costs:
            return
        try:
            if spans:
                self._post("/v1/traces", tracing.to_otlp(spans))
            if costs:
                self._post("/v1/costs", {"records": costs})
            METRICS.inc("telemetry_push_total", outcome="ok")
            with self._lock:
                self._backoff_s = 0.0
        except Exception:  # noqa: BLE001 — collector down ≠ serving down
            METRICS.inc("telemetry_push_total", outcome="error")
            self._requeue(self._spans, "span", spans)
            self._requeue(self._costs, "cost", costs)
            with self._lock:
                self._backoff_s = min(
                    _BACKOFF_CAP_S,
                    (self._backoff_s or _BACKOFF_BASE_S) * 2)

    def _post(self, path: str, doc: dict) -> None:
        req = urllib.request.Request(
            self.url + path, data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        # graftlint: allow(direct-io): telemetry export to an EXTERNAL
        # collector, not a cluster RPC — it must not ride the peer
        # breaker/retry wrapper; this loop has its own bounded
        # retry/backoff/drop policy
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            r.read()

    def status(self) -> dict:
        alive = self._thread is not None and self._thread.is_alive()
        with self._lock:
            return {"url": self.url, "interval_s": self.interval_s,
                    "buffered_spans": len(self._spans),
                    "buffered_costs": len(self._costs),
                    "backoff_s": self._backoff_s,
                    "alive": alive,
                    "last_cycle_age_s": round(
                        time.monotonic() - self._last_cycle_mono, 3)}
