"""Leveled logging wrappers.

Reference parity: `x/log.go` glog-style leveled logging. Thin stdlib
`logging` setup with the reference's severity prefixes, so operator
tooling that greps I/W/E lines keeps working.
"""

from __future__ import annotations

import logging
import sys

_FMT = "%(levelname).1s%(asctime)s %(name)s %(message)s"
_DATEFMT = "%m%d %H:%M:%S"

_configured = False


def setup(level: str = "info") -> None:
    global _configured
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter(_FMT, _DATEFMT))
    root = logging.getLogger("dgraph_tpu")
    root.handlers[:] = [h]
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    _configured = True


def get(name: str) -> logging.Logger:
    if not _configured:
        setup()
    return logging.getLogger(f"dgraph_tpu.{name}")
