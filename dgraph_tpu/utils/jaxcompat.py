"""Version-compat resolvers for the jax APIs the mesh layer rides.

`shard_map` is the one API the whole `parallel/` package is built on,
and it has moved twice across jax releases: it started life as
`jax.experimental.shard_map.shard_map` (with a `check_rep` kwarg),
then graduated to `jax.shard_map` (renaming the kwarg to `check_vma`).
The jax build this repo pins (0.4.x) only ships the experimental
spelling, while the code is written against the graduated one — so
every import of this module resolves ONE callable, whichever spelling
the running jax provides, and translates the kwarg.

This file is the ONLY place allowed to touch either spelling directly:
graftlint rule R7 (`shard-map-compat`, analysis/rules.py) makes a
direct `jax.shard_map` / `jax.experimental.shard_map` reference
anywhere else a finding, so the mesh layer cannot silently regress the
next time jax moves the API.
"""

from __future__ import annotations

import inspect

import jax

__all__ = ["shard_map", "SHARD_MAP_ORIGIN"]


def _resolve():
    impl = getattr(jax, "shard_map", None)
    if impl is not None:
        origin = "jax.shard_map"
    else:
        from jax.experimental.shard_map import shard_map as impl
        origin = "jax.experimental.shard_map.shard_map"
    try:
        params = frozenset(inspect.signature(impl).parameters)
    except (TypeError, ValueError):  # C-accelerated / wrapped callables
        params = frozenset()
    return impl, origin, params


_IMPL, SHARD_MAP_ORIGIN, _PARAMS = _resolve()


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` resolved across jax versions.

    Callers use the graduated signature (`check_vma`); on builds that
    only have the experimental API the flag is forwarded as its old
    name `check_rep` (same meaning: per-output replication checking).
    """
    if "check_vma" in _PARAMS:
        return _IMPL(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_vma=check_vma)
    if "check_rep" in _PARAMS:
        return _IMPL(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=check_vma)
    return _IMPL(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
