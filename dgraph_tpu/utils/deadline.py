"""Request lifecycle: deadlines + cooperative cancellation.

Reference parity: the reference enforces request lifecycles with Go
`context.Context` — every `worker.Task` gRPC leg carries a deadline, and
a query that outlives it is cancelled cooperatively at loop boundaries
(`ctx.Err()` checks in ProcessGraph / processTask). Python has no
ambient context, so this module provides one: a `RequestContext` with a
MONOTONIC deadline and a thread-safe cancel flag, installed thread-local
by the serving layer (`Alpha._request`) and consulted by `checkpoint()`
calls in the hot loops — level expansions, BFS iterations, kernel-group
launches, cluster RPC legs.

Checkpoint granularity is one level / one BFS iteration / one RPC: a
pathological `@recurse` or shortest-path query stops within one loop
body of its budget instead of holding the Alpha until it finishes.
Everything a cancelled request held (read registrations, admission
tokens, fold gates) is released by the enclosing `with`/`finally`
blocks it raises through — cancellation is an exception, never a
thread kill.

Budget forwarding: the remaining budget rides outbound cluster RPCs as
the gRPC timeout (server/task.py Client._call) and is re-established on
the receiving peer from `ServicerContext.time_remaining()` — the Go
context propagation analog, without a proto change.

Both `DeadlineExceeded` and `Cancelled` are RETRYABLE by contract: the
server refused to spend more than the client's budget; nothing
half-applied (the mutate path checkpoints only BEFORE the two-phase
stage begins — interrupting between stage and decide would leak an
undecided pend, so once staging starts the decision protocol runs to
completion).
"""

from __future__ import annotations

import contextlib
import threading
import time

from dgraph_tpu.utils.metrics import METRICS

__all__ = ["RequestContext", "DeadlineExceeded", "Cancelled",
           "current", "activate", "checkpoint", "remaining_s",
           "monotonic_s"]


def monotonic_s() -> float:
    """The package's one blessed clock for deadline/backoff/elapsed
    arithmetic (graftlint R3 wall-clock): NTP steps and DST never move
    a budget. Wall clock (`time.time`) is reserved for timestamps that
    leave the process (span epochs, cross-process token expiry) and
    every such site carries a reasoned waiver."""
    return time.monotonic()


class DeadlineExceeded(Exception):
    """RETRYABLE: the request's time budget expired mid-flight. The
    partially-done work was discarded cleanly (no leaked read
    registrations, pends, or admission tokens); retry with a larger
    budget."""

    def __init__(self, msg: str, stage: str = ""):
        super().__init__(msg)
        self.stage = stage


class Cancelled(Exception):
    """RETRYABLE: the client cancelled the request (connection drop,
    explicit cancel). Same cleanup contract as DeadlineExceeded."""

    def __init__(self, msg: str, stage: str = ""):
        super().__init__(msg)
        self.stage = stage


class RequestContext:
    """One request's budget: monotonic deadline + cancel flag.

    `deadline_ms=None` (or 0) means unbounded — `check()` then only
    honors the cancel flag. The cancel flag is an Event so any thread
    (an HTTP handler noticing a closed socket, an operator endpoint)
    can cancel a request executing elsewhere."""

    __slots__ = ("started", "deadline", "_cancel")

    def __init__(self, deadline_ms: float | None = None):
        self.started = time.monotonic()
        self.deadline = (self.started + deadline_ms / 1e3
                         if deadline_ms else None)
        self._cancel = threading.Event()

    def cancel(self) -> None:
        self._cancel.set()

    @property
    def cancelled(self) -> bool:
        return self._cancel.is_set()

    def remaining_s(self) -> float | None:
        """Seconds of budget left (None = unbounded; ≤ 0 = expired).
        This is what outbound RPC legs forward to peers."""
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()

    def remaining_ms(self) -> float | None:
        r = self.remaining_s()
        return None if r is None else r * 1e3

    def expired(self) -> bool:
        return (self.deadline is not None
                and time.monotonic() >= self.deadline)

    def consume(self, seconds: float) -> None:
        """VIRTUALLY advance this request's clock by `seconds`: the
        deadline moves earlier by exactly that much, so budget
        arithmetic (checkpoints, RPC timeout forwarding, admission
        waits) behaves as if the time had really passed — without any
        wall-clock sleep. This is the clock-free delay-fault primitive
        (cluster/fault.py): a fuzzed 30 ms link stall costs the fuzz
        run zero wall time but still expires tight budgets exactly
        like a real stall. Unbounded contexts have no budget to
        consume; the caller's drop counter still records the event."""
        if self.deadline is not None and seconds > 0:
            self.deadline -= seconds

    def check(self, stage: str = "") -> None:
        """Raise (retryably) if the budget is gone — the cooperative
        cancellation point. Metrics label the STAGE that noticed, so an
        overrunning workload names its hot loop."""
        if self._cancel.is_set():
            METRICS.inc("request_cancelled_total", stage=stage)
            raise Cancelled(f"request cancelled at stage "
                            f"{stage or 'unknown'}", stage=stage)
        if self.deadline is not None:
            now = time.monotonic()
            if now >= self.deadline:
                METRICS.inc("deadline_exceeded_total", stage=stage)
                raise DeadlineExceeded(
                    f"deadline exceeded at stage {stage or 'unknown'} "
                    f"({(now - self.started) * 1e3:.1f} ms elapsed, "
                    f"budget "
                    f"{(self.deadline - self.started) * 1e3:.1f} ms); "
                    f"retry with a larger deadline", stage=stage)


_TLS = threading.local()
# thread ident → active context, for CROSS-thread cancellation (an HTTP
# connection watcher noticing a closed socket must cancel the request
# context its HANDLER thread will create/has created). Plain dict: a
# single store+pop per request, CPython-atomic.
_ACTIVE: dict[int, RequestContext] = {}


def current() -> RequestContext | None:
    """The thread's active RequestContext (None outside any request)."""
    return getattr(_TLS, "ctx", None)


def of_thread(ident: int) -> RequestContext | None:
    """The ACTIVE RequestContext of another thread (None when that
    thread is not inside a request) — the cross-thread cancellation
    handle; `ctx.cancel()` is thread-safe."""
    return _ACTIVE.get(ident)


@contextlib.contextmanager
def activate(ctx: RequestContext):
    """Install `ctx` as the thread's ambient request context."""
    prev = getattr(_TLS, "ctx", None)
    ident = threading.get_ident()
    _TLS.ctx = ctx
    _ACTIVE[ident] = ctx
    try:
        yield ctx
    finally:
        _TLS.ctx = prev
        if prev is None:
            _ACTIVE.pop(ident, None)
        else:
            _ACTIVE[ident] = prev


def checkpoint(stage: str = "") -> None:
    """Cooperative cancellation point for hot loops: one thread-local
    load + None check when no request context is active (the
    observability-overhead bar applies here too — tier-1 guards the
    uncontended path at <5%)."""
    ctx = getattr(_TLS, "ctx", None)
    if ctx is not None:
        ctx.check(stage)


def remaining_s() -> float | None:
    """Remaining budget of the ambient context (None = unbounded or no
    context) — what transports forward to peers."""
    ctx = getattr(_TLS, "ctx", None)
    return None if ctx is None else ctx.remaining_s()
