"""Lock-order sanitizer: instrumented locks for the whole stack.

Reference parity: the reference leans on `go test -race` to keep its
heavily-threaded worker/zero/posting layers honest; Python has no race
detector, but the failure mode our ~17 lock sites can actually produce
is a lock-ORDER inversion (thread 1 takes A then B, thread 2 takes B
then A — a deadlock that only fires under the right interleaving, i.e.
in production). This module is the dynamic half of graftlint
(dgraph_tpu/analysis): every lock site in cluster/, store/, server/ and
utils/ creates its lock through `make_lock(name)` /`make_rlock` /
`make_condition`, which return plain `threading` primitives in
production and instrumented wrappers when `DGRAPH_TPU_LOCK_SANITIZER=1`
(tests/conftest.py arms it for the whole tier-1 suite and the partition
fuzzer).

What the instrumented wrappers record, per thread, at acquire time:

* **Acquisition-order edges** — when a thread acquires lock B while
  holding lock A, the edge A→B enters a process-global graph, keyed by
  lock NAME (every instance created at one site shares a name, so the
  graph captures the site's order discipline, not object identities).
  The first sighting of an edge captures the full acquisition stack;
  `LockGraph.cycles()` then reports every order cycle with the stack of
  EACH participating edge — both sides of an inversion, not just the
  one that happened to deadlock.
* **Hold times** — a lock held longer than `DGRAPH_TPU_LOCK_HOLD_MS`
  (default 250) is recorded with its release-site stack; long holds are
  surfaced (`/debug/locks`, `snapshot()`), never failed on — a WAL
  fsync under io pressure is information, not a bug.

Design constraints: this module imports NOTHING from dgraph_tpu
(metrics/tracing create their registries' locks through it — any
upward import would cycle), and the instrumented fast path never calls
back into metrics (releasing the metrics registry's own traced lock
must not recurse into the registry). Reentrant acquisition of the same
instance (RLock) records no self-edge; same-name edges between distinct
instances are skipped too — instances of one site form one order class.

Caveat (documented, accepted): `threading.Lock` allows releasing from a
different thread than the acquirer; the sanitizer pops by identity and
ignores an unmatched release, so cross-thread hand-offs degrade to
unrecorded holds instead of corrupting the graph.

**Race sanitizer (ISSUE 12) — the Eraser lockset half.** Lock ORDER
catches deadlocks; the classic production failure is an unguarded
access to shared state. `DGRAPH_TPU_RACE_SANITIZER=1` (requires the
lock sanitizer too — locksets come from TracedLock's bookkeeping) arms
`guarded(obj, lock_name)`, called once per `__init__` of every class
the static inference (dgraph_tpu/analysis/guards.py) lists in its
lock-discipline inventory. Arming swaps the instance onto a cached
subclass whose inventory fields are data descriptors; every
read/write of those fields records (field, thread, currently-held
lockset) and runs the Eraser state machine per field:

    virgin → exclusive (first thread; no checks — the init window)
           → shared (second thread reads)      C(v) ∩= held
           → shared-modified (any later write) C(v) ∩= held, and an
             EMPTY C(v) here is a data race, reported with BOTH
             access stacks (the last lockset-relevant access and the
             racing one).

The lockset-refinement design means benign patterns stay silent:
lock-handoff (every access under the same lock keeps C(v) nonempty)
and publish-then-freeze (writes by one thread, then cross-thread
reads only, never reaches shared-modified). Accesses whose caller
frame lives under tests/ are exempt — the harness peeks internals at
quiescent points (`assert not a._pending`) and must not convict the
package. Off (`guarded()` returns immediately, no subclass swap) the
fields are plain attributes: zero overhead. `RACES.snapshot()` backs
`GET /debug/races`; tests/conftest.py arms the whole tier-1 suite and
fails the session on any report, and both fuzzers assert race-free
across every historical seed.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback

__all__ = ["enabled", "make_lock", "make_rlock", "make_condition",
           "GRAPH", "LockGraph", "TracedLock", "TracedRLock",
           "set_enabled", "race_enabled", "guarded", "attach",
           "RACES", "RaceTable", "set_race_enabled"]

ENV_SWITCH = "DGRAPH_TPU_LOCK_SANITIZER"
ENV_RACE_SWITCH = "DGRAPH_TPU_RACE_SANITIZER"
ENV_HOLD_MS = "DGRAPH_TPU_LOCK_HOLD_MS"
MAX_LONG_HOLDS = 64          # bounded report ring — newest wins
MAX_RACE_REPORTS = 64        # bounded race list — first wins (root cause)
_STACK_SKIP = 2              # drop the sanitizer's own frames


def enabled() -> bool:
    """Is the sanitizer armed for NEW locks? (Checked at lock-creation
    time: flipping the env var mid-process affects locks made after.)"""
    return os.environ.get(ENV_SWITCH, "") not in ("", "0")


def _stack() -> str:
    return "".join(traceback.format_stack()[:-_STACK_SKIP])


class LockGraph:
    """Process-global acquisition-order graph + long-hold ring.

    Thread-held stacks live in a `threading.local`; the graph structure
    is guarded by a PLAIN lock (never a traced one — the sanitizer must
    not sanitize itself) that is only taken on the slow paths: first
    sighting of an edge, a long hold, a snapshot."""

    def __init__(self, hold_threshold_ms: float | None = None):
        self._glock = threading.Lock()
        self._tls = threading.local()
        if hold_threshold_ms is None:
            hold_threshold_ms = float(
                os.environ.get(ENV_HOLD_MS, "") or 250.0)
        self.hold_threshold_s = hold_threshold_ms / 1e3
        # (held_name, acquired_name) → {"count", "stack"} — stack is the
        # first-sighting acquisition stack of the SECOND lock
        self.edges: dict[tuple[str, str], dict] = {}
        self.long_holds: list[dict] = []
        self.acquires = 0            # total instrumented acquisitions
        self.recording = True

    def set_enabled(self, flag: bool) -> None:
        """Disarm recording (the <5% overhead guard's off switch).
        Already-held entries release tolerantly while disarmed."""
        self.recording = bool(flag)

    # -- hot path ------------------------------------------------------------
    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def note_acquire(self, lock) -> None:
        """Called AFTER the inner primitive was acquired."""
        if not self.recording:
            return
        held = self._held()
        self.acquires += 1
        reentrant = any(e[0] is lock for e in held)
        if not reentrant and held:
            seen_names = set()
            for entry in held:
                a = entry[0].name
                b = lock.name
                if a == b or a in seen_names:
                    continue
                seen_names.add(a)
                key = (a, b)
                e = self.edges.get(key)   # racy read: fine, edge keys
                if e is not None:         # are write-once + count bump
                    e["count"] += 1
                else:
                    with self._glock:
                        if key not in self.edges:
                            self.edges[key] = {"count": 1,
                                               "stack": _stack()}
                        else:
                            self.edges[key]["count"] += 1
        held.append((lock, time.monotonic(), reentrant))
        # bump the per-thread held-set version (the race sanitizer
        # caches its lockset-by-name off it — one int add here saves
        # a frozenset build per tracked field access over there)
        self._tls.ver = getattr(self._tls, "ver", 0) + 1

    def note_release(self, lock) -> None:
        held = getattr(self._tls, "held", None)
        if not held:
            return
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                _, t0, _reent = held.pop(i)
                self._tls.ver = getattr(self._tls, "ver", 0) + 1
                if not self.recording:
                    return
                dt = time.monotonic() - t0
                if dt >= self.hold_threshold_s:
                    with self._glock:
                        if len(self.long_holds) >= MAX_LONG_HOLDS:
                            self.long_holds.pop(0)
                        self.long_holds.append(
                            {"lock": lock.name,
                             "held_ms": round(dt * 1e3, 3),
                             "stack": _stack()})
                return
        # unmatched release (cross-thread hand-off, or recording was
        # off at acquire time): tolerated, see module docstring

    # -- reporting -----------------------------------------------------------
    def cycles(self) -> list[dict]:
        """Every distinct lock-order cycle in the recorded graph, each
        with the acquisition stack of EVERY participating edge. Empty
        list == no inversion was ever observed."""
        with self._glock:
            edges = {k: dict(v) for k, v in self.edges.items()}
        adj: dict[str, list[str]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
        out, seen_cycles = [], set()

        def dfs(node: str, path: list[str], on_path: set):
            for nxt in adj.get(node, ()):
                if nxt in on_path:
                    cyc = path[path.index(nxt):]
                    key = frozenset(cyc)
                    if key in seen_cycles:
                        continue
                    seen_cycles.add(key)
                    ring = cyc + [nxt]
                    out.append({
                        "cycle": cyc,
                        "edges": [
                            {"from": ring[i], "to": ring[i + 1],
                             "count": edges[(ring[i],
                                             ring[i + 1])]["count"],
                             "stack": edges[(ring[i],
                                             ring[i + 1])]["stack"]}
                            for i in range(len(cyc))],
                    })
                elif nxt not in visited:
                    visited.add(nxt)
                    on_path.add(nxt)
                    dfs(nxt, path + [nxt], on_path)
                    on_path.discard(nxt)

        visited: set[str] = set()
        for start in sorted(adj):
            if start not in visited:
                visited.add(start)
                dfs(start, [start], {start})
        return out

    def snapshot(self) -> dict:
        """Graph + long-hold state for `/debug/locks` (stacks trimmed
        to their last line for the edge table; cycles keep full ones)."""
        with self._glock:
            edges = [{"from": a, "to": b, "count": e["count"]}
                     for (a, b), e in sorted(self.edges.items())]
            holds = list(self.long_holds)
        return {
            "enabled": enabled(),
            "recording": self.recording,
            "acquires_total": self.acquires,
            "edges": edges,
            "cycles": self.cycles(),
            "long_holds": [{k: v for k, v in h.items() if k != "stack"}
                           for h in holds],
            "hold_threshold_ms": self.hold_threshold_s * 1e3,
        }

    def reset(self) -> None:
        """Test hook: forget edges and holds (held stacks survive — a
        reset under live threads must not orphan their releases)."""
        with self._glock:
            self.edges.clear()
            self.long_holds.clear()
            self.acquires = 0


GRAPH = LockGraph()


def set_enabled(flag: bool) -> None:
    GRAPH.set_enabled(flag)


class TracedLock:
    """`threading.Lock` plus order/hold recording. Supports the full
    acquire signature so `threading.Condition` can wrap it."""

    __slots__ = ("_inner", "name", "_graph")
    _factory = staticmethod(threading.Lock)

    def __init__(self, name: str, graph: LockGraph | None = None):
        self._inner = self._factory()
        self.name = name
        self._graph = graph if graph is not None else GRAPH

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._graph.note_acquire(self)
        return ok

    def release(self) -> None:
        self._graph.note_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r} {self._inner!r}>"


class TracedRLock(TracedLock):
    """Reentrant flavor: nested acquisition by the owner records no
    self-edge (note_acquire detects the instance already on the held
    stack) and hold time measures the OUTERMOST span."""

    __slots__ = ()
    _factory = staticmethod(threading.RLock)

    def locked(self) -> bool:  # RLock has no locked() before 3.12
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


def make_lock(name: str) -> "threading.Lock | TracedLock":
    """The one lock constructor every subsystem uses: a plain
    `threading.Lock` in production, a `TracedLock` under the sanitizer.
    `name` is the site's order-class (e.g. "mvcc.store")."""
    return TracedLock(name) if enabled() else threading.Lock()


def make_rlock(name: str) -> "threading.RLock | TracedRLock":
    return TracedRLock(name) if enabled() else threading.RLock()


def make_condition(name: str) -> threading.Condition:
    """A Condition whose underlying lock participates in the order
    graph (wait() releases/reacquires through the traced wrapper)."""
    if enabled():
        return threading.Condition(TracedLock(name))
    return threading.Condition()


# ---------------------------------------------------------------------------
# Eraser lockset race sanitizer (ISSUE 12) — see module docstring

def race_enabled() -> bool:
    """Is the race sanitizer armed for NEW guarded() calls? Requires
    the lock sanitizer too: the per-thread lockset IS TracedLock's
    held bookkeeping — without it every lockset reads empty and every
    shared field would convict."""
    return (os.environ.get(ENV_RACE_SWITCH, "") not in ("", "0")
            and enabled())


# Eraser field states
_EXCLUSIVE, _SHARED, _SHARED_MOD = 0, 1, 2
_STATE_KEY = "_race_state"   # per-instance {field: state dict}


class _RaceField:
    """Data descriptor standing in for ONE tracked field on a shim
    subclass: every get/set records the access, then reads/writes the
    plain value in the instance dict (a data descriptor shadows the
    instance dict, so storage and interception never recurse).
    Untracked attributes of the same object ride the normal lookup
    path untouched."""

    __slots__ = ("name", "table")

    def __init__(self, name: str, table: "RaceTable"):
        self.name = name
        self.table = table

    def __get__(self, obj, owner=None):
        if obj is None:
            return self
        self.table.note(obj, self.name, False)
        try:
            return obj.__dict__[self.name]
        except KeyError:
            raise AttributeError(self.name) from None

    def __set__(self, obj, value):
        self.table.note(obj, self.name, True)
        obj.__dict__[self.name] = value

    def __delete__(self, obj):
        self.table.note(obj, self.name, True)
        del obj.__dict__[self.name]


class RaceTable:
    """Per-field Eraser lockset state machine + the bounded report
    list. Field state lives ON the instance (`_race_state` dict) so
    object death retires its state — id() reuse can never alias two
    objects' histories into a false race. The report path is the only
    slow path; candidate-set updates are dict ops under the GIL, and
    a torn update can only MISS an intersection (a report requires
    two real accesses with disjoint locksets, which is a discipline
    violation by itself — no false positive is constructible)."""

    def __init__(self, graph: LockGraph | None = None,
                 exempt_tests: bool = False):
        self._glock = threading.Lock()  # reports/registry, never hot
        self.graph = graph if graph is not None else GRAPH
        self.reports: list[dict] = []
        self.races_total = 0
        self.recording = True
        # the process-global table skips direct field peeks from test
        # frames (see note()); private tables in synthetic race tests
        # must check EVERY access, including the test's own
        self.exempt_tests = exempt_tests
        # original class -> shim subclass; (file, class) -> arming info
        self._shims: dict = {}
        self.registered: dict = {}
        # per-thread token: threading.get_ident() RECYCLES after a
        # thread exits, which would let a later thread alias a dead
        # owner and park a field in the exclusive state (a missed
        # race); these tokens are issued once per thread lifetime and
        # never reused
        self._tok_tls = threading.local()
        self._tok_iter = iter(range(1, 1 << 62))

    def _tid(self) -> int:
        t = getattr(self._tok_tls, "tok", None)
        if t is None:
            t = self._tok_tls.tok = next(self._tok_iter)
        return t

    def set_enabled(self, flag: bool) -> None:
        """Disarm recording (the <5% overhead guard's off switch) —
        descriptors stay installed; note() returns immediately."""
        self.recording = bool(flag)

    # -- hot path -------------------------------------------------------------
    _EMPTY = frozenset()

    def _held_names(self) -> frozenset:
        """The calling thread's held lockset by name, cached against
        the graph's per-thread acquire/release version — a lock
        section with several tracked accesses builds the set once."""
        tls = self.graph._tls
        held = getattr(tls, "held", None)
        if not held:
            return self._EMPTY
        ver = getattr(tls, "ver", 0)
        cache = getattr(tls, "names_cache", None)
        if cache is not None and cache[0] == ver:
            return cache[1]
        names = frozenset(e[0].name for e in held)
        tls.names_cache = (ver, names)
        return names

    def _from_test(self) -> bool:
        """Harness exemption (global table only): a DIRECT field peek
        from test code (the fuzz harness asserting `not a._pending`
        at a quiescent point) is instrumentation, not package
        discipline — package-internal accesses triggered BY tests
        still have package frames at the access site and stay fully
        checked. Only consulted when an access is about to CHANGE
        state or report, so the steady-state hot path never walks a
        frame."""
        caller = sys._getframe(3).f_code.co_filename
        return "/tests/" in caller or caller.endswith("conftest.py")

    def note(self, obj, field: str, write: bool) -> None:
        if not self.recording:
            return
        tid = self._tid()
        states = obj.__dict__.get(_STATE_KEY)
        if states is None:
            states = obj.__dict__[_STATE_KEY] = {}
        s = states.get(field)
        if s is None:
            if self.exempt_tests and self._from_test():
                return
            # first tracked access: exclusive to this thread, no
            # checks — Eraser's initialization window. Its stack is
            # kept: it is "the other side" of a race surfacing at the
            # very first cross-thread write.
            states[field] = {"mode": _EXCLUSIVE, "owner": tid,
                             "set": None, "stack": _stack(),
                             "stack_tid": tid, "stack_held": (),
                             "reported": False}
            return
        mode = s["mode"]
        if mode == _EXCLUSIVE:
            if s["owner"] == tid:
                return  # fast path: still single-threaded
            if self.exempt_tests and self._from_test():
                return
            # second thread arrives: leave the init window
            held = self._held_names()
            s["set"] = held
            s["mode"] = _SHARED_MOD if write else _SHARED
            if s["mode"] == _SHARED_MOD and not held \
                    and not s["reported"]:
                self._report(obj, field, s, tid, held, write)
                return
            s["stack"] = _stack()
            s["stack_tid"] = tid
            s["stack_held"] = tuple(sorted(held))
            return
        held = self._held_names()
        new = s["set"] & held
        flip = write and mode == _SHARED
        if new == s["set"] and not flip:
            # steady state — nothing would change; the only possible
            # event is an access racing an already-empty set
            if mode == _SHARED_MOD and not new and not s["reported"]:
                if self.exempt_tests and self._from_test():
                    return
                self._report(obj, field, s, tid, held, write)
            return
        # a shrink and/or the shared→shared-modified flip is imminent:
        # now (and only now) pay the harness-exemption frame walk
        if self.exempt_tests and self._from_test():
            return
        if flip:
            s["mode"] = _SHARED_MOD
        shrank = new != s["set"]
        if shrank:
            s["set"] = new
        if s["mode"] == _SHARED_MOD and not new and not s["reported"]:
            self._report(obj, field, s, tid, held, write)
            return
        if shrank:
            # this access shrank the candidate set: it is one of the
            # two accesses that prove any upcoming race
            s["stack"] = _stack()
            s["stack_tid"] = tid
            s["stack_held"] = tuple(sorted(held))

    # -- reporting ------------------------------------------------------------
    def _report(self, obj, field, s, tid, held, write) -> None:
        s["reported"] = True  # one report per field, not a flood
        with self._glock:
            self.races_total += 1
            if len(self.reports) >= MAX_RACE_REPORTS:
                return
            self.reports.append({
                "class": type(obj).__name__,
                "field": field,
                "lock": getattr(type(obj), "_race_lock_", "?"),
                "kind": "write" if write else "read",
                "first": {"thread": s["stack_tid"],
                          "lockset": list(s["stack_held"]),
                          "stack": s["stack"] or ""},
                "second": {"thread": tid,
                           "lockset": sorted(held),
                           "stack": _stack()},
            })

    def snapshot(self) -> dict:
        with self._glock:
            reports = [dict(r) for r in self.reports]
            tracked = sorted(f"{file}:{cls}"
                             for file, cls in self.registered)
        return {
            "enabled": race_enabled(),
            "recording": self.recording,
            "races_total": self.races_total,
            "tracked_classes": tracked,
            "reports": reports,
        }

    def reset(self) -> None:
        """Test hook: forget reports (shims and per-object state
        survive — live objects keep their histories)."""
        with self._glock:
            self.reports.clear()
            self.races_total = 0

    # -- arming ---------------------------------------------------------------
    def attach(self, obj, fields, lock_name: str) -> None:
        """Install the field-access shim on one instance: swap its
        class for a cached subclass carrying a _RaceField descriptor
        per tracked field. Values already in the instance dict stay
        where they are — the descriptor reads/writes the same slot."""
        cls = type(obj)
        if getattr(cls, "_race_shim_", False):
            return  # already armed (re-registration is a no-op)
        sub = self._shims.get((cls, tuple(fields)))
        if sub is None:
            ns = {f: _RaceField(f, self) for f in fields}
            ns["_race_shim_"] = True
            ns["_race_lock_"] = lock_name
            sub = type(cls.__name__, (cls,), ns)
            with self._glock:
                self._shims.setdefault((cls, tuple(fields)), sub)
                sub = self._shims[(cls, tuple(fields))]
        obj.__class__ = sub

    def register(self, obj, lock_name: str) -> None:
        """The `guarded()` slow path: resolve the statically-inferred
        field inventory for this object's class (walking the MRO —
        `WAL(Journal)` arms Journal's fields) and attach the shim."""
        from dgraph_tpu.analysis.guards import runtime_inventory
        inv = runtime_inventory()
        fields: list = []
        hit_key = None
        for klass in type(obj).__mro__:
            mod = getattr(klass, "__module__", "") or ""
            if not mod.startswith("dgraph_tpu"):
                continue
            key = (mod.replace(".", "/") + ".py", klass.__name__)
            entry = inv.get(key)
            if entry is None:
                continue
            hit_key = hit_key or key
            for info in entry["locks"].values():
                fields.extend(f for f in info["fields"]
                              if f not in fields)
        if hit_key is None:
            return  # no inferred discipline: nothing to arm
        with self._glock:
            self.registered[hit_key] = {
                "lock": lock_name, "fields": tuple(sorted(fields))}
        self.attach(obj, fields, lock_name)


RACES = RaceTable(exempt_tests=True)


def set_race_enabled(flag: bool) -> None:
    RACES.set_enabled(flag)


def attach(obj, fields, lock_name: str,
           table: RaceTable | None = None) -> None:
    """Test-facing shim installer with an explicit field list and an
    optional private table (synthetic races must not trip the
    session gate)."""
    (table if table is not None else RACES).attach(
        obj, tuple(fields), lock_name)


def guarded(obj, lock_name: str):
    """Arm one instance for Eraser lockset checking, using the
    statically-inferred guarded-field inventory for its class. Called
    once at the end of `__init__` by every class the inventory lists;
    a PLAIN no-op (and plain attributes) unless
    DGRAPH_TPU_RACE_SANITIZER=1 and the lock sanitizer is armed.
    Returns `obj` so call sites can wrap construction."""
    if race_enabled():
        RACES.register(obj, lock_name)
    return obj
