"""Lock-order sanitizer: instrumented locks for the whole stack.

Reference parity: the reference leans on `go test -race` to keep its
heavily-threaded worker/zero/posting layers honest; Python has no race
detector, but the failure mode our ~17 lock sites can actually produce
is a lock-ORDER inversion (thread 1 takes A then B, thread 2 takes B
then A — a deadlock that only fires under the right interleaving, i.e.
in production). This module is the dynamic half of graftlint
(dgraph_tpu/analysis): every lock site in cluster/, store/, server/ and
utils/ creates its lock through `make_lock(name)` /`make_rlock` /
`make_condition`, which return plain `threading` primitives in
production and instrumented wrappers when `DGRAPH_TPU_LOCK_SANITIZER=1`
(tests/conftest.py arms it for the whole tier-1 suite and the partition
fuzzer).

What the instrumented wrappers record, per thread, at acquire time:

* **Acquisition-order edges** — when a thread acquires lock B while
  holding lock A, the edge A→B enters a process-global graph, keyed by
  lock NAME (every instance created at one site shares a name, so the
  graph captures the site's order discipline, not object identities).
  The first sighting of an edge captures the full acquisition stack;
  `LockGraph.cycles()` then reports every order cycle with the stack of
  EACH participating edge — both sides of an inversion, not just the
  one that happened to deadlock.
* **Hold times** — a lock held longer than `DGRAPH_TPU_LOCK_HOLD_MS`
  (default 250) is recorded with its release-site stack; long holds are
  surfaced (`/debug/locks`, `snapshot()`), never failed on — a WAL
  fsync under io pressure is information, not a bug.

Design constraints: this module imports NOTHING from dgraph_tpu
(metrics/tracing create their registries' locks through it — any
upward import would cycle), and the instrumented fast path never calls
back into metrics (releasing the metrics registry's own traced lock
must not recurse into the registry). Reentrant acquisition of the same
instance (RLock) records no self-edge; same-name edges between distinct
instances are skipped too — instances of one site form one order class.

Caveat (documented, accepted): `threading.Lock` allows releasing from a
different thread than the acquirer; the sanitizer pops by identity and
ignores an unmatched release, so cross-thread hand-offs degrade to
unrecorded holds instead of corrupting the graph.
"""

from __future__ import annotations

import os
import threading
import time
import traceback

__all__ = ["enabled", "make_lock", "make_rlock", "make_condition",
           "GRAPH", "LockGraph", "TracedLock", "TracedRLock",
           "set_enabled"]

ENV_SWITCH = "DGRAPH_TPU_LOCK_SANITIZER"
ENV_HOLD_MS = "DGRAPH_TPU_LOCK_HOLD_MS"
MAX_LONG_HOLDS = 64          # bounded report ring — newest wins
_STACK_SKIP = 2              # drop the sanitizer's own frames


def enabled() -> bool:
    """Is the sanitizer armed for NEW locks? (Checked at lock-creation
    time: flipping the env var mid-process affects locks made after.)"""
    return os.environ.get(ENV_SWITCH, "") not in ("", "0")


def _stack() -> str:
    return "".join(traceback.format_stack()[:-_STACK_SKIP])


class LockGraph:
    """Process-global acquisition-order graph + long-hold ring.

    Thread-held stacks live in a `threading.local`; the graph structure
    is guarded by a PLAIN lock (never a traced one — the sanitizer must
    not sanitize itself) that is only taken on the slow paths: first
    sighting of an edge, a long hold, a snapshot."""

    def __init__(self, hold_threshold_ms: float | None = None):
        self._glock = threading.Lock()
        self._tls = threading.local()
        if hold_threshold_ms is None:
            hold_threshold_ms = float(
                os.environ.get(ENV_HOLD_MS, "") or 250.0)
        self.hold_threshold_s = hold_threshold_ms / 1e3
        # (held_name, acquired_name) → {"count", "stack"} — stack is the
        # first-sighting acquisition stack of the SECOND lock
        self.edges: dict[tuple[str, str], dict] = {}
        self.long_holds: list[dict] = []
        self.acquires = 0            # total instrumented acquisitions
        self.recording = True

    def set_enabled(self, flag: bool) -> None:
        """Disarm recording (the <5% overhead guard's off switch).
        Already-held entries release tolerantly while disarmed."""
        self.recording = bool(flag)

    # -- hot path ------------------------------------------------------------
    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def note_acquire(self, lock) -> None:
        """Called AFTER the inner primitive was acquired."""
        if not self.recording:
            return
        held = self._held()
        self.acquires += 1
        reentrant = any(e[0] is lock for e in held)
        if not reentrant and held:
            seen_names = set()
            for entry in held:
                a = entry[0].name
                b = lock.name
                if a == b or a in seen_names:
                    continue
                seen_names.add(a)
                key = (a, b)
                e = self.edges.get(key)   # racy read: fine, edge keys
                if e is not None:         # are write-once + count bump
                    e["count"] += 1
                else:
                    with self._glock:
                        if key not in self.edges:
                            self.edges[key] = {"count": 1,
                                               "stack": _stack()}
                        else:
                            self.edges[key]["count"] += 1
        held.append((lock, time.monotonic(), reentrant))

    def note_release(self, lock) -> None:
        held = getattr(self._tls, "held", None)
        if not held:
            return
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                _, t0, _reent = held.pop(i)
                if not self.recording:
                    return
                dt = time.monotonic() - t0
                if dt >= self.hold_threshold_s:
                    with self._glock:
                        if len(self.long_holds) >= MAX_LONG_HOLDS:
                            self.long_holds.pop(0)
                        self.long_holds.append(
                            {"lock": lock.name,
                             "held_ms": round(dt * 1e3, 3),
                             "stack": _stack()})
                return
        # unmatched release (cross-thread hand-off, or recording was
        # off at acquire time): tolerated, see module docstring

    # -- reporting -----------------------------------------------------------
    def cycles(self) -> list[dict]:
        """Every distinct lock-order cycle in the recorded graph, each
        with the acquisition stack of EVERY participating edge. Empty
        list == no inversion was ever observed."""
        with self._glock:
            edges = {k: dict(v) for k, v in self.edges.items()}
        adj: dict[str, list[str]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
        out, seen_cycles = [], set()

        def dfs(node: str, path: list[str], on_path: set):
            for nxt in adj.get(node, ()):
                if nxt in on_path:
                    cyc = path[path.index(nxt):]
                    key = frozenset(cyc)
                    if key in seen_cycles:
                        continue
                    seen_cycles.add(key)
                    ring = cyc + [nxt]
                    out.append({
                        "cycle": cyc,
                        "edges": [
                            {"from": ring[i], "to": ring[i + 1],
                             "count": edges[(ring[i],
                                             ring[i + 1])]["count"],
                             "stack": edges[(ring[i],
                                             ring[i + 1])]["stack"]}
                            for i in range(len(cyc))],
                    })
                elif nxt not in visited:
                    visited.add(nxt)
                    on_path.add(nxt)
                    dfs(nxt, path + [nxt], on_path)
                    on_path.discard(nxt)

        visited: set[str] = set()
        for start in sorted(adj):
            if start not in visited:
                visited.add(start)
                dfs(start, [start], {start})
        return out

    def snapshot(self) -> dict:
        """Graph + long-hold state for `/debug/locks` (stacks trimmed
        to their last line for the edge table; cycles keep full ones)."""
        with self._glock:
            edges = [{"from": a, "to": b, "count": e["count"]}
                     for (a, b), e in sorted(self.edges.items())]
            holds = list(self.long_holds)
        return {
            "enabled": enabled(),
            "recording": self.recording,
            "acquires_total": self.acquires,
            "edges": edges,
            "cycles": self.cycles(),
            "long_holds": [{k: v for k, v in h.items() if k != "stack"}
                           for h in holds],
            "hold_threshold_ms": self.hold_threshold_s * 1e3,
        }

    def reset(self) -> None:
        """Test hook: forget edges and holds (held stacks survive — a
        reset under live threads must not orphan their releases)."""
        with self._glock:
            self.edges.clear()
            self.long_holds.clear()
            self.acquires = 0


GRAPH = LockGraph()


def set_enabled(flag: bool) -> None:
    GRAPH.set_enabled(flag)


class TracedLock:
    """`threading.Lock` plus order/hold recording. Supports the full
    acquire signature so `threading.Condition` can wrap it."""

    __slots__ = ("_inner", "name", "_graph")
    _factory = staticmethod(threading.Lock)

    def __init__(self, name: str, graph: LockGraph | None = None):
        self._inner = self._factory()
        self.name = name
        self._graph = graph if graph is not None else GRAPH

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._graph.note_acquire(self)
        return ok

    def release(self) -> None:
        self._graph.note_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r} {self._inner!r}>"


class TracedRLock(TracedLock):
    """Reentrant flavor: nested acquisition by the owner records no
    self-edge (note_acquire detects the instance already on the held
    stack) and hold time measures the OUTERMOST span."""

    __slots__ = ()
    _factory = staticmethod(threading.RLock)

    def locked(self) -> bool:  # RLock has no locked() before 3.12
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


def make_lock(name: str) -> "threading.Lock | TracedLock":
    """The one lock constructor every subsystem uses: a plain
    `threading.Lock` in production, a `TracedLock` under the sanitizer.
    `name` is the site's order-class (e.g. "mvcc.store")."""
    return TracedLock(name) if enabled() else threading.Lock()


def make_rlock(name: str) -> "threading.RLock | TracedRLock":
    return TracedRLock(name) if enabled() else threading.RLock()


def make_condition(name: str) -> threading.Condition:
    """A Condition whose underlying lock participates in the order
    graph (wait() releases/reacquires through the traced wrapper)."""
    if enabled():
        return threading.Condition(TracedLock(name))
    return threading.Condition()
