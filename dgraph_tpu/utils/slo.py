"""Declarative SLOs evaluated with multi-window burn rates.

The stack's metrics answer "what is happening"; the time-series ring
(utils/timeseries.py) retains "what has been happening"; this module
closes the loop with the Google-SRE alerting discipline on top of that
history: each SLO names an objective (a per-lane latency target, an
error-rate budget, a shed-rate budget), and the engine evaluates its
BURN RATE — the fraction of the error budget consumed per unit time —
over two windows at once (fast ~5m, slow ~1h, both scaled down for
tests). A fast-window burn above its threshold pages (here: bumps
`slo_breaches_total{slo=,window=}`, emits a `slo.breach` flight event
carrying an exemplar trace id from the slow-query ring, and — when
sustained across evaluations — convicts via the flight-recorder
watchdog, kind=slo). The slow window catches the quiet bleed a fast
spike never shows.

Spec inventory discipline (the `cost_record_fields` pattern): the
static `SLO_SPECS` inventory below is re-exported verbatim by
`analysis/facts.py` as `facts.slo_specs`, graftlint rule R15 rejects
literal SLO names outside it, and tests/test_lint.py pins the runtime
evaluator registry to the inventory in BOTH directions — an SLO that
evaluates but isn't inventoried (or an inventoried name nothing
evaluates) fails tier-1.

Import discipline: importable without jax (facts extraction and the
analysis CLI read `SLO_SPECS` with no device runtime); the exemplar
lookup and flightrec emission import lazily at breach time only.
"""

from __future__ import annotations

from dgraph_tpu.utils import locks
from dgraph_tpu.utils.metrics import METRICS

__all__ = ["SLO_SPECS", "DEFAULT_TARGETS", "SloEngine", "parse_spec",
           "install", "uninstall", "ENGINE",
           "FAST_WINDOW_S", "SLOW_WINDOW_S", "FAST_BURN", "SLOW_BURN"]

# ---------------------------------------------------------------------------
# static inventory: every SLO the engine can evaluate, by name.
# graftlint R15 pins this both ways — `analysis/facts.py` re-exports it
# verbatim and the runtime evaluator registry must cover exactly these
# names — so an alerting objective cannot ship undocumented (the
# cost_record_fields pattern, same as memgov.GOVERNED_CACHES).

SLO_SPECS: dict[str, str] = {
    "read_latency_p99_us": "p99 latency objective for read-lane queries "
                           "(µs target over the query_latency_us "
                           "histogram; 1% of requests may exceed it)",
    "mutate_latency_p99_us": "p99 latency objective for mutations (µs "
                             "target over the mutation leg of the "
                             "query_latency_us histogram)",
    "error_rate": "fraction of served requests that errored "
                  "(query_errors_total over the request total) the "
                  "budget tolerates before burning",
    "shed_rate": "fraction of admission arrivals shed "
                 "(shed_total over admission_requests_total) — load "
                 "shedding is budgeted, not free",
    "graphrag_read_p99": "p99 latency objective for GraphRAG retrieval "
                         "blocks — similar_to-seeded queries, any route "
                         "(µs target over the graphrag_latency_us "
                         "histogram; 1% may exceed it)",
}

# default objectives (overridable per-name via --slo_spec superflag):
# latency targets in µs; rate SLOs as allowed bad fractions
DEFAULT_TARGETS: dict[str, float] = {
    "read_latency_p99_us": 100_000.0,
    "mutate_latency_p99_us": 250_000.0,
    "error_rate": 0.01,
    "shed_rate": 0.05,
    "graphrag_read_p99": 150_000.0,
}

# a pN latency SLO tolerates (100-N)% of requests over target — the
# bad-fraction budget burn rates are computed against
_LATENCY_BUDGET = 0.01

# Google-SRE multi-window defaults: a fast 5-minute window paging at
# 14× burn (budget gone in ~2 days at that pace) and a slow 1-hour
# window ticketing at 2× — both scaled down by tests via the ctor
FAST_WINDOW_S = 300.0
SLOW_WINDOW_S = 3600.0
FAST_BURN = 14.0
SLOW_BURN = 2.0
# consecutive fast-breached evaluations before the watchdog may
# convict (kind=slo) — one spiky window is a page, not a conviction
SUSTAIN_EVALS = 3


def parse_spec(s: str) -> dict[str, float]:
    """`--slo_spec` superflag → per-name target overrides. Unknown SLO
    names are REJECTED (a typo must not silently leave the default
    budget in force)."""
    from dgraph_tpu.utils.config import parse_superflag
    out: dict[str, float] = {}
    for k, v in parse_superflag(s or "").items():
        if k not in SLO_SPECS:
            raise ValueError(f"unknown SLO {k!r} — add it to "
                             f"slo.SLO_SPECS")
        out[k] = float(v)
    return out


# ---------------------------------------------------------------------------
# runtime evaluator registry: spec name → (window view, target) →
# (bad_events, total_events). Registration validates against the
# inventory, mirroring memgov.Governor.register.

_EVALUATORS: dict = {}


def _evaluator(name: str):
    if name not in SLO_SPECS:
        raise ValueError(f"unknown SLO {name!r} — add it to "
                         f"slo.SLO_SPECS")

    def deco(fn):
        _EVALUATORS[name] = fn
        return fn
    return deco


@_evaluator("read_latency_p99_us")
def _eval_read_latency(view, target: float):
    return view.frac_above("query_latency_us{endpoint=\"query\"",
                           target)


@_evaluator("mutate_latency_p99_us")
def _eval_mutate_latency(view, target: float):
    return view.frac_above("query_latency_us{endpoint=\"mutate\"",
                           target)


@_evaluator("error_rate")
def _eval_error_rate(view, target: float):
    bad = view.delta("query_errors_total")
    total = view.hist_n("query_latency_us") + bad
    return bad, total


@_evaluator("shed_rate")
def _eval_shed_rate(view, target: float):
    return (view.delta("shed_total"),
            view.delta("admission_requests_total"))


@_evaluator("graphrag_read_p99")
def _eval_graphrag_latency(view, target: float):
    return view.frac_above("graphrag_latency_us", target)


def _budget_fraction(name: str, target: float) -> float:
    """The allowed bad fraction a burn of 1.0 consumes exactly: for
    latency SLOs the pN tail budget; for rate SLOs the target IS the
    budget."""
    if name.endswith("_us") or name.endswith("_p99"):
        return _LATENCY_BUDGET
    return max(target, 1e-9)


def _exemplar() -> str:
    """Best-effort trace id to pin on a breach: the newest slow-query
    ring entry (the request most likely to BE the regression), falling
    back to the newest finished cost record. Lazy imports — the server
    module chain (jax) only loads in a process that serves."""
    try:
        from dgraph_tpu.server.http import slow_queries_snapshot
        entries = slow_queries_snapshot()
        if entries:  # ring appends newest last
            return entries[-1].get("trace_id", "") or ""
    except Exception:
        pass
    try:
        from dgraph_tpu.utils import costprofile
        recs = costprofile.recent(1)
        if recs:
            return recs[0].get("trace_id", "") or ""
    except Exception:
        pass
    return ""


class SloEngine:
    """Evaluates every inventoried SLO against the time-series ring's
    fast and slow windows; owns the breach lifecycle (edge-triggered
    metrics + flight events, sustained-burn conviction feed)."""

    def __init__(self, targets: dict[str, float] | None = None,
                 fast_window_s: float = FAST_WINDOW_S,
                 slow_window_s: float = SLOW_WINDOW_S,
                 fast_burn: float = FAST_BURN,
                 slow_burn: float = SLOW_BURN,
                 sustain_evals: int = SUSTAIN_EVALS):
        self.targets = dict(DEFAULT_TARGETS)
        for k, v in (targets or {}).items():
            if k not in SLO_SPECS:
                raise ValueError(f"unknown SLO {k!r} — add it to "
                                 f"slo.SLO_SPECS")
            self.targets[k] = float(v)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_thresholds = {"fast": float(fast_burn),
                                "slow": float(slow_burn)}
        self.sustain_evals = int(sustain_evals)
        self._lock = locks.make_lock("slo.engine")
        self._states: dict[str, dict] = {}
        self._consec_fast: dict[str, int] = {}
        self._breached: dict[tuple[str, str], bool] = {}
        self.breaches_total = 0
        locks.guarded(self, "slo.engine")

    # -- evaluation -------------------------------------------------------

    def evaluate(self, ring, now: float | None = None) -> dict:
        """One evaluation pass over every SLO × both windows. `ring` is
        the timeseries.Ring; deterministic given its points (tests pass
        fabricated rings)."""
        views = {"fast": ring.window(self.fast_window_s, now=now),
                 "slow": ring.window(self.slow_window_s, now=now)}
        states: dict[str, dict] = {}
        events: list[tuple[str, str, dict]] = []
        with self._lock:
            for name in sorted(SLO_SPECS):
                target = self.targets[name]
                budget = _budget_fraction(name, target)
                st: dict = {"target": target, "budget": budget,
                            "windows": {}}
                fast_breached = False
                for win, view in views.items():
                    bad, total = _EVALUATORS[name](view, target)
                    frac = (bad / total) if total else 0.0
                    burn = frac / budget
                    threshold = self.burn_thresholds[win]
                    breached = total > 0 and burn >= threshold
                    st["windows"][win] = {
                        "bad": bad, "total": total,
                        "bad_frac": round(frac, 6),
                        "burn": round(burn, 4),
                        "threshold": threshold,
                        "breached": breached,
                        "span_s": round(view.span_s, 3)}
                    key = (name, win)
                    if breached and not self._breached.get(key):
                        events.append((name, win, st["windows"][win]))
                    self._breached[key] = breached
                    if win == "fast":
                        fast_breached = breached
                if fast_breached:
                    self._consec_fast[name] = (
                        self._consec_fast.get(name, 0) + 1)
                else:
                    self._consec_fast[name] = 0
                st["consec_fast"] = self._consec_fast[name]
                states[name] = st
            self._states = states
            self.breaches_total += len(events)
        for name, st in states.items():
            for win, w in st["windows"].items():
                METRICS.set_gauge("slo_burn_rate", w["burn"],
                                  slo=name, window=win)
        for name, win, w in events:
            self._on_breach(name, win, w)
        return states

    def _on_breach(self, name: str, win: str, w: dict) -> None:
        """Edge-triggered breach: count it and flight-record it with an
        exemplar trace id resolvable at /debug/traces?trace_id=."""
        METRICS.inc("slo_breaches_total", slo=name, window=win)
        trace_id = _exemplar()
        try:
            from dgraph_tpu.utils import flightrec
            flightrec.emit("slo.breach", slo=name, window=win,
                           burn=w["burn"], bad=w["bad"],
                           total=w["total"], target=self.targets[name],
                           trace_id=trace_id)
        except Exception:
            pass

    # -- watchdog feed ----------------------------------------------------

    def convictable(self) -> list[dict]:
        """SLOs whose FAST burn has stayed breached for sustain_evals
        consecutive evaluations — what the flight-recorder watchdog
        convicts as kind=slo (utils/flightrec.py `_scan_slo`)."""
        out = []
        with self._lock:
            for name, n in self._consec_fast.items():
                if n >= self.sustain_evals:
                    st = self._states.get(name, {})
                    fast = st.get("windows", {}).get("fast", {})
                    out.append({"slo": name, "consec_fast": n,
                                "burn": fast.get("burn", 0.0),
                                "target": self.targets[name]})
        return out

    def status(self) -> dict:
        """The /debug/slo document."""
        with self._lock:
            return {"specs": {n: {"doc": SLO_SPECS[n],
                                  "target": self.targets[n]}
                              for n in sorted(SLO_SPECS)},
                    "windows": {"fast_s": self.fast_window_s,
                                "slow_s": self.slow_window_s},
                    "burn_thresholds": dict(self.burn_thresholds),
                    "states": self._states,
                    "breaches_total": self.breaches_total}


# the armed engine (None = disarmed): the watchdog's kind=slo scan and
# /debug/slo read this — one global load + None check when disarmed
ENGINE: SloEngine | None = None


def install(engine: SloEngine) -> SloEngine:
    global ENGINE
    ENGINE = engine
    return engine


def uninstall() -> None:
    global ENGINE
    ENGINE = None
