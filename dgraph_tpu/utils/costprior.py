"""Per-shape cost priors: the DECIDING half of the cost-model item.

PR 8 built the dataset (utils/costprofile.py: shape-keyed percentile
digests of measured per-request cost, joined with the plan features that
predict it). This module turns the digests into PRIORS the scheduler can
consult BEFORE running a request — the TpuGraphs direction (PAPERS):
predict execution cost from static plan features, here applied to our
own serving loop. Three consumers:

* **Admission** (`server/admission.py` via `Alpha._request`): shedding
  decisions and Retry-After hints use the arriving request's predicted
  cost instead of the lane-wide service-time EMA — a cheap lookup no
  longer queues behind (or gets shed because of) a fleet of expensive
  recurse shapes.
* **Batch planner** (`engine/batch.py`): kernel groups are gated and
  ordered by predicted cost, not query count, and lane-pack imbalance
  is gauged per batch.
* **Placement** (`cluster/zero.py`): per-tablet cost sums ride the
  health heartbeat so Zero moves tablets toward healthy, under-loaded
  peers.

The prior itself is deliberately cheap and dependency-free: per shape
fingerprint, a percentile BLEND of the digest (p50 + BLEND·(p90−p50) —
tail-aware without chasing p99 noise), refit incrementally as requests
complete (EMA toward the observed cost) and refit exactly from the
digests on boot/merge. Shapes below `sample_floor` observations fall
back to a per-lane EMA of observed request cost (which itself replaces
the admission lane's idle-stale EMA). A weighted least-squares fit of
cost against the per-shape FEATURE means (the TpuGraphs-style static
regressors — `FEATURES`, pinned to `costprofile.FIELDS` by graftlint
facts + tests/test_lint.py) covers shapes the digests have never seen
but whose plan features are known at launch time.

Prediction accuracy is tracked (absolute + relative error digests) and
surfaced at `GET /debug/scheduler` with live hit/fallback counts
(`cost_prior_hits_total` / `cost_prior_fallbacks_total`). The model
persists as `costpriors.json` beside `costprofiles.json` and merges
back on boot exactly as the digests do.

Whole-query fusion (ISSUE 15, engine/fused.py) composes with all of
this for free: a fused request records a `fused` shape component, so
its digests — and therefore the priors fit from them — key per
PROGRAM (shape `fused+q:...`, `kernel_launches == 1`) while the
staged runs of the same template keep their per-kernel-chain shape.
Admission predictions and the batch planner's cost gates sharpen as
the fused route warms, with no new code path here: the shape
vocabulary IS the mechanism.
"""

from __future__ import annotations

import json

from dgraph_tpu.utils import costprofile, locks
from dgraph_tpu.utils.costprofile import Digest
from dgraph_tpu.utils.metrics import MAX_LABEL_SETS, METRICS

__all__ = ["FEATURES", "SAMPLE_FLOOR", "BLEND", "CostPriorModel",
           "PRIORS", "enabled", "set_enabled", "predict", "lane_ema_us",
           "learn",
           "refit", "status", "save", "load", "reset"]

# ONE feature vocabulary with the runtime cost records: the prior's
# regressors ARE costprofile's feature fields (re-exported by
# analysis/facts.py as `cost_prior_features`; tests/test_lint.py pins
# the two in sync both ways, like `cost_record_fields`).
FEATURES = tuple(costprofile.FEATURE_FIELDS)

SAMPLE_FLOOR = 8         # observations before a shape prior is trusted
BLEND = 0.5              # predicted = p50 + BLEND * (p90 - p50)
_EMA_ALPHA = 0.2         # incremental refit smoothing (per shape + lane)
_LANE_SEED_US = 50_000.0  # lane fallback before any observation (50 ms)
_TEXT_MEMO_MAX = 2048    # query-text → shape memo entries


class CostPriorModel:
    """Shape-keyed cost priors with lane-EMA fallback (see module doc).
    The module-level `PRIORS` instance is the process-wide registry
    (METRICS/COSTS-style); tests construct their own."""

    def __init__(self, sample_floor: int = SAMPLE_FLOOR,
                 max_shapes: int = MAX_LABEL_SETS):
        self._lock = locks.make_lock("costprior.model")
        self.sample_floor = int(sample_floor)
        self.max_shapes = int(max_shapes)
        # shape → {"n", "predicted_us", "p50", "p90"}
        self._shapes: dict[str, dict] = {}
        # lane → EMA of observed request µs (the admission fallback)
        self._lane_ema: dict[str, float] = {}
        # execution route (mesh/device/numpy/...) → EMA of measured µs
        # per 1k edges: the engine's route selector consults these to
        # promote the mesh route below its static frontier threshold
        # (engine/execute.py _mesh_promoted)
        self._route_ema: dict[str, float] = {}
        # query-text hash → shape fingerprint, learned as requests
        # complete (admission predicts BEFORE parsing; the memo is how
        # a repeated template's shape is known pre-parse). Insertion
        # order doubles as the FIFO eviction order.
        self._text_shape: dict[int, str] = {}
        # prediction-accuracy tracking (prior hits only): absolute µs
        # error digest + relative error in 0.1% units
        self._abs_err = Digest()
        self._rel_err = Digest()
        self.hits = 0
        self.fallbacks = 0
        self.refits = 0
        # weighted least-squares fit of p50 cost on feature means
        # (unseen-shape predictor for the batch planner)
        self._fit: dict | None = None
        locks.guarded(self, "costprior.model")

    # -- prediction ----------------------------------------------------------
    def shape_for_text(self, text: str) -> str | None:
        with self._lock:
            return self._text_shape.get(hash(text))

    def predict(self, lane: str, text: str | None = None,
                shape: str | None = None) -> tuple[float, str]:
        """(predicted µs, source): source is "prior" when a trusted
        shape prior answered, else "fallback" (lane EMA). Never raises
        and never parses — one memo lookup + one dict lookup."""
        with self._lock:
            if shape is None and text is not None:
                shape = self._text_shape.get(hash(text))
            p = self._shapes.get(shape) if shape else None
            if p is not None and p["n"] >= self.sample_floor:
                self.hits += 1
                METRICS.inc("cost_prior_hits_total", lane=lane)
                return float(p["predicted_us"]), "prior"
            self.fallbacks += 1
            METRICS.inc("cost_prior_fallbacks_total", lane=lane)
            return float(self._lane_ema.get(lane, _LANE_SEED_US)), \
                "fallback"

    def predict_shape(self, shape: str) -> float | None:
        """Trusted per-shape prediction or None — the batch planner's
        lookup (its fallback is the feature fit, then query count)."""
        with self._lock:
            p = self._shapes.get(shape)
            if p is not None and p["n"] >= self.sample_floor:
                return float(p["predicted_us"])
            return None

    def lane_ema_us(self, lane: str) -> float | None:
        """The lane's observed-cost EMA, or None before any completed
        request — the watchdog's prediction fallback for requests that
        arrived without a costprior prediction (utils/flightrec.py)."""
        with self._lock:
            v = self._lane_ema.get(lane)
            return float(v) if v is not None else None

    def predict_features(self, features: dict) -> float | None:
        """Linear-model prediction from plan features (known at launch
        time even for never-digested shapes), or None before a fit."""
        with self._lock:
            fit = self._fit
        if fit is None:
            return None
        us = fit["intercept"]
        for f, w in fit["coef"].items():
            us += w * float(features.get(f, 0))
        return max(us, 0.0)

    # -- route costs (the expansion-path selector's prior) -------------------
    def learn_route(self, path: str, us_per_kedge: float) -> None:
        """Fold one expansion's measured µs-per-1k-edges into the
        path's EMA (called from engine ops.expand on every route)."""
        with self._lock:
            ema = self._route_ema.get(path)
            self._route_ema[path] = (
                float(us_per_kedge) if ema is None
                else ema + _EMA_ALPHA * (float(us_per_kedge) - ema))

    def route_cost(self, path: str) -> float | None:
        """Measured µs/1k-edges EMA for an execution route, or None
        before any observation."""
        with self._lock:
            return self._route_ema.get(path)

    # -- learning ------------------------------------------------------------
    def learn(self, lane: str, text: str | None, shape: str | None,
              actual_us: float, predicted_us: float | None = None,
              source: str | None = None) -> None:
        """Fold one COMPLETED request back in: remember text→shape,
        update the lane EMA and the shape's incremental prior, and —
        when the prediction came from a prior — record its error."""
        actual_us = float(actual_us)
        with self._lock:
            if text is not None and shape:
                h = hash(text)
                if h not in self._text_shape and \
                        len(self._text_shape) >= _TEXT_MEMO_MAX:
                    self._text_shape.pop(next(iter(self._text_shape)))
                self._text_shape[h] = shape
            ema = self._lane_ema.get(lane)
            self._lane_ema[lane] = (actual_us if ema is None
                                    else ema + _EMA_ALPHA
                                    * (actual_us - ema))
            if shape:
                p = self._shapes.get(shape)
                if p is None:
                    if len(self._shapes) >= self.max_shapes:
                        return
                    p = self._shapes[shape] = {
                        "n": 0, "predicted_us": actual_us,
                        "p50": actual_us, "p90": actual_us}
                p["n"] += 1
                p["predicted_us"] += _EMA_ALPHA * (actual_us
                                                   - p["predicted_us"])
            if predicted_us is not None and source == "prior":
                self._abs_err.add(abs(actual_us - predicted_us))
                self._rel_err.add(1000.0 * abs(actual_us - predicted_us)
                                  / max(actual_us, 1.0))

    # -- refit from digests --------------------------------------------------
    def refit(self, agg=None, overwrite: bool = True) -> dict:
        """Exact refit from an Aggregator's total_us digests: per shape,
        predicted = p50 + BLEND·(p90−p50). Deterministic for a fixed
        digest set (pinned by tests/test_costprior.py). With
        overwrite=False only shapes the model has never seen are filled
        in (the boot path: the merged costpriors.json keeps its
        incrementally-refined values). Also (re)fits the feature
        least-squares model. Returns a fit summary."""
        import numpy as np
        agg = agg if agg is not None else costprofile.COSTS
        rows_x, rows_y, rows_w = [], [], []
        fitted = 0
        with agg._lock:
            shape_stats = {s: (st.count,
                               st.digests["total_us"].percentile(0.50),
                               st.digests["total_us"].percentile(0.90),
                               dict(st.features))
                           for s, st in agg._shapes.items()}
        with self._lock:
            for shape, (n, p50, p90, feats) in shape_stats.items():
                if not n:
                    continue
                if shape not in self._shapes \
                        and len(self._shapes) >= self.max_shapes:
                    continue
                if overwrite or shape not in self._shapes:
                    self._shapes[shape] = {
                        "n": n,
                        "predicted_us": float(p50 + BLEND * (p90 - p50)),
                        "p50": int(p50), "p90": int(p90)}
                    fitted += 1
                # the fit tolerates a lower bar than per-shape trust:
                # a weighted point with few samples still informs the
                # regression more than silence does
                if n >= max(3, self.sample_floor // 2):
                    rows_x.append([feats.get(f, 0) / n for f in FEATURES]
                                  + [1.0])
                    rows_y.append(float(p50))
                    rows_w.append(float(n))
            self.refits += 1
        fit = None
        if len(rows_x) >= 3:
            x = np.asarray(rows_x, np.float64)
            y = np.asarray(rows_y, np.float64)
            w = np.sqrt(np.asarray(rows_w, np.float64))
            coef, *_ = np.linalg.lstsq(x * w[:, None], y * w,
                                       rcond=None)
            pred = x @ coef
            ss_res = float(((y - pred) ** 2).sum())
            ss_tot = float(((y - y.mean()) ** 2).sum())
            fit = {"coef": {f: round(float(c), 4)
                            for f, c in zip(FEATURES, coef[:-1])},
                   "intercept": round(float(coef[-1]), 2),
                   "r2": round(1.0 - ss_res / ss_tot, 4)
                   if ss_tot > 0 else 0.0,
                   "shapes": len(rows_x)}
            with self._lock:
                self._fit = fit
        return {"shapes_fitted": fitted,
                "shapes_total": len(shape_stats), "fit": fit}

    # -- persistence (beside costprofiles.json) ------------------------------
    def to_state(self) -> dict:
        with self._lock:
            return {"version": 1,
                    "shapes": {s: dict(p)
                               for s, p in self._shapes.items()},
                    "lane_ema": dict(self._lane_ema),
                    "route_ema": dict(self._route_ema)}

    def merge_state(self, state: dict) -> None:
        """Merge a persisted model (boot path): per shape, n-weighted
        mean of predictions; lane EMAs average when both sides exist."""
        for shape, p in state.get("shapes", {}).items():
            n_in = max(int(p.get("n", 0)), 0)
            with self._lock:
                mine = self._shapes.get(shape)
                if mine is None:
                    if len(self._shapes) < self.max_shapes:
                        self._shapes[shape] = {
                            "n": n_in,
                            "predicted_us": float(
                                p.get("predicted_us", 0.0)),
                            "p50": int(p.get("p50", 0)),
                            "p90": int(p.get("p90", 0))}
                    continue
                tot = mine["n"] + n_in
                if tot:
                    mine["predicted_us"] = (
                        mine["predicted_us"] * mine["n"]
                        + float(p.get("predicted_us", 0.0)) * n_in) / tot
                mine["n"] = tot
                mine["p50"] = max(mine["p50"], int(p.get("p50", 0)))
                mine["p90"] = max(mine["p90"], int(p.get("p90", 0)))
        with self._lock:
            for lane, v in state.get("lane_ema", {}).items():
                mine_v = self._lane_ema.get(lane)
                self._lane_ema[lane] = (float(v) if mine_v is None
                                        else (mine_v + float(v)) / 2.0)
            for path, v in state.get("route_ema", {}).items():
                mine_v = self._route_ema.get(path)
                self._route_ema[path] = (float(v) if mine_v is None
                                         else (mine_v + float(v)) / 2.0)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_state(), f)

    def load(self, path: str) -> bool:
        """Merge a persisted model into this one. A missing file is a
        silent no-op; a corrupt/truncated or wrong-shaped one is
        COUNTED and logged but still never aborts the boot — priors
        are telemetry-derived, the model refits from digests (ISSUE-11
        sidecar hardening)."""
        try:
            with open(path) as f:
                state = json.load(f)
            self.merge_state(state)
        except OSError:
            return False
        except Exception:  # noqa: BLE001 — corrupt sidecar: start fresh
            import os

            from dgraph_tpu.utils import logging as xlog
            from dgraph_tpu.utils.metrics import METRICS
            METRICS.inc("sidecar_load_failures_total",
                        file=os.path.basename(path))
            xlog.get("costprior").warning(
                "corrupt cost-prior sidecar %s ignored; refitting "
                "from digests", path, exc_info=True)
            return False
        return True

    # -- surfacing (/debug/scheduler) ----------------------------------------
    def status(self, top_n: int = 10) -> dict:
        with self._lock:
            shapes = sorted(self._shapes.items(),
                            key=lambda kv: kv[1]["predicted_us"],
                            reverse=True)
            return {
                "shapes": len(self._shapes),
                "hits": self.hits,
                "fallbacks": self.fallbacks,
                "refits": self.refits,
                "sample_floor": self.sample_floor,
                "lane_ema_us": {ln: round(v, 1)
                                for ln, v in self._lane_ema.items()},
                "route_us_per_kedge": {p: round(v, 2)
                                       for p, v in
                                       self._route_ema.items()},
                "error": {
                    "n": self._abs_err.count,
                    "abs_p50_us": self._abs_err.percentile(0.50),
                    "abs_p90_us": self._abs_err.percentile(0.90),
                    "rel_p50_pct": self._rel_err.percentile(0.50) / 10.0,
                    "rel_p90_pct": self._rel_err.percentile(0.90) / 10.0,
                },
                "fit": self._fit,
                "top": [{"shape": s, "n": p["n"],
                         "predicted_us": round(p["predicted_us"], 1)}
                        for s, p in shapes[:top_n]],
            }

    def clear(self) -> None:
        with self._lock:
            self._shapes.clear()
            self._lane_ema.clear()
            self._route_ema.clear()
            self._text_shape.clear()
            self._abs_err = Digest()
            self._rel_err = Digest()
            self.hits = self.fallbacks = self.refits = 0
            self._fit = None


# -- process-wide registry + module-level convenience wrappers ---------------

PRIORS = CostPriorModel()
_ENABLED = True


def enabled() -> bool:
    return _ENABLED


def set_enabled(flag: bool) -> None:
    """Process-wide off switch (`--cost_priors` plumbs here; per-Alpha
    opt-out rides `Alpha.cost_priors`). Disabling stops predictions —
    admission falls back to its own lane EMA — but keeps learned state."""
    global _ENABLED
    _ENABLED = bool(flag)


def predict(lane: str, text: str | None = None,
            shape: str | None = None) -> tuple[float, str]:
    return PRIORS.predict(lane, text=text, shape=shape)


def lane_ema_us(lane: str) -> float | None:
    return PRIORS.lane_ema_us(lane)


def learn(lane: str, text: str | None, shape: str | None,
          actual_us: float, predicted_us: float | None = None,
          source: str | None = None) -> None:
    PRIORS.learn(lane, text, shape, actual_us,
                 predicted_us=predicted_us, source=source)


def refit(agg=None, overwrite: bool = True) -> dict:
    return PRIORS.refit(agg=agg, overwrite=overwrite)


def status(top_n: int = 10) -> dict:
    return PRIORS.status(top_n=top_n)


def save(path: str) -> None:
    PRIORS.save(path)


def load(path: str) -> bool:
    return PRIORS.load(path)


def reset() -> None:
    """Test hook: forget every prior, memo, and counter."""
    PRIORS.clear()
