"""dgraph_tpu — a TPU-native distributed graph-query framework.

Provides the capabilities of the reference graph database (Dgraph,
`ashishgandhi/dgraph`) — predicate-sharded posting lists, DQL multi-hop
queries (expand / @filter / @recurse / shortest / pagination / aggregation),
MVCC transactions, uid leasing, loaders — re-designed TPU-first:

- Posting lists are predicate-sharded CSR blocks in HBM (reference:
  `posting/list.go` + `codec/codec.go` varint blocks).
- One query hop = one jit-compiled sparse-gather + sorted-set program over
  the whole frontier (reference: `query.SubGraph.ProcessGraph` +
  `algo.IntersectSorted` per-uid Go loops).
- Cross-device movement is XLA collectives over the ICI mesh
  (reference: inter-Alpha gRPC fan-out in `worker.ProcessTaskOverNetwork`).

Layer map (see SURVEY.md §1):
  ops/      sorted-uid algebra + hop kernels      (algo/, codec/)
  store/    CSR posting store, schema, types, tok (posting/, schema/, types/, tok/)
  engine/   SubGraph execution, recurse, shortest (query/)
  dql/      lexer + DQL parser                    (lex/, gql/)
  parallel/ mesh sharding + collective hops       (worker/ distribution)
  cluster/  oracle: uid/ts leases, tablets        (dgraph/cmd/zero/)
  server/   public API + task service             (edgraph/, worker/server.go)
  loader/   RDF/JSON chunker, live/bulk, xidmap   (chunker/, dgraph/cmd/{live,bulk}/, xidmap/)
  models/   built-in graph workload generators    (benchmarks fixtures)
  utils/    config, metrics, logging, tracing     (x/)
  native/   C++ host runtime (nquad parse, codec) (hot Go loops)

Observability (x/metrics.go + OpenCensus spans in the reference): the
query path emits spans (utils/tracing — unique span ids, per-request
trace ids echoed in responses, Chrome trace-event export at
/debug/events) and labeled Prometheus metrics (utils/metrics, served
at /debug/prometheus_metrics); /debug/traces resolves a response's
trace id to its engine/op/RPC spans, and --slow_query_ms logs slow
queries with their trace id.
"""

__version__ = "0.1.0"
