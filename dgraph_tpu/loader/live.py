"""Live loader: stream mutations through the transaction path.

Reference parity: `dgraph/cmd/live/run.go` — chunk the input RDF/JSON,
batch N-Quads per mutation, fire batches with bounded concurrency and
abort-retry, xidmap for blank/external ids. Works against an in-process
`Alpha` or a remote gRPC `Client` (same surface the reference's live
loader has against an Alpha endpoint).
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

from dgraph_tpu.loader.chunker import parse_rdf
from dgraph_tpu.server.api import Alpha, TxnAborted


@dataclass
class LiveStats:
    nquads: int = 0
    txns: int = 0
    aborts: int = 0
    elapsed_s: float = 0.0


def run_live(alpha: Alpha, rdf_text: str, batch_size: int = 1000,
             concurrency: int = 4, max_retries: int = 5) -> LiveStats:
    """Load N-Quad text through live mutations (reference: live.run)."""
    t0 = time.perf_counter()
    nquads = parse_rdf(rdf_text)
    stats = LiveStats(nquads=len(nquads))

    # batch on subject boundaries so one subject's statements commit
    # together (reference batches arbitrarily; subject-aligned batching
    # avoids cross-batch blank-node references)
    batches: list[list] = []
    cur: list = []
    cur_subjects: set[str] = set()
    for nq in nquads:
        if len(cur) >= batch_size and nq.subject not in cur_subjects:
            batches.append(cur)
            cur, cur_subjects = [], set()
        cur.append(nq)
        cur_subjects.add(nq.subject)
    if cur:
        batches.append(cur)

    # blank nodes must resolve consistently ACROSS batches: pre-assign
    # through the shared xidmap (the reference does exactly this)
    def to_rdf(batch) -> str:
        lines = []
        for nq in batch:
            s = nq.subject
            if s.startswith("_:"):
                s = f"0x{alpha.xidmap.assign(s):x}"
            o = nq.object_id
            if o and o.startswith("_:"):
                o = f"0x{alpha.xidmap.assign(o):x}"
            if nq.is_star:
                lines.append(f"<{s}> <{nq.predicate}> * .")
            elif o is not None:
                lines.append(f"<{s}> <{nq.predicate}> <{o}> .")
            else:
                v = str(nq.object_value).replace("\\", "\\\\").replace(
                    '"', '\\"')
                lit = f'"{v}"'
                if isinstance(nq.object_value, bool):
                    lit = f'"{str(nq.object_value).lower()}"^^<xs:boolean>'
                elif isinstance(nq.object_value, int):
                    lit += "^^<xs:int>"
                elif isinstance(nq.object_value, float):
                    lit += "^^<xs:float>"
                elif nq.lang:
                    lit += f"@{nq.lang}"
                lines.append(f"<{s}> <{nq.predicate}> {lit} .")
        return "\n".join(lines)

    def fire(batch) -> None:
        rdf = to_rdf(batch)
        for attempt in range(max_retries):
            try:
                alpha.mutate(set_nquads=rdf, commit_now=True)
                stats.txns += 1
                return
            except TxnAborted:
                stats.aborts += 1
                time.sleep(0.01 * (attempt + 1))
        raise TxnAborted(f"batch failed after {max_retries} retries")

    with ThreadPoolExecutor(max_workers=concurrency) as pool:
        list(pool.map(fire, batches))
    stats.elapsed_s = time.perf_counter() - t0
    return stats
