"""Bulk loader: offline map-reduce RDF → checkpointed Store snapshot.

Reference parity: `dgraph/cmd/bulk/` — N mapper PROCESSES shard-parse
N-Quads (the map phase is pure-Python lexing, so real processes, not
GIL-bound threads — the role of bulk's mapper goroutines), the
single-process reduce assigns uids and builds CSR blocks + columnar
values (what HBM wants), written via `store.checkpoint.save` as the
snapshot Alphas boot from.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import time
from dataclasses import dataclass

from dgraph_tpu.cluster.oracle import Oracle
from dgraph_tpu.loader.chunker import NQuad, parse_rdf
from dgraph_tpu.loader.xidmap import XidMap
from dgraph_tpu.store import checkpoint
from dgraph_tpu.store.schema import Schema, parse_schema
from dgraph_tpu.store.store import Store, StoreBuilder


@dataclass
class BulkStats:
    nquads: int = 0
    nodes: int = 0
    edges: int = 0
    elapsed_s: float = 0.0


def chunk_lines(text: str, n_chunks: int) -> list[str]:
    """Split N-Quad text on line boundaries into ~equal chunks
    (reference: chunker feeding N mapper goroutines)."""
    lines = text.splitlines()
    per = max(1, -(-len(lines) // max(n_chunks, 1)))
    return ["\n".join(lines[i:i + per]) for i in range(0, len(lines), per)]


def _map_chunk(chunk: str) -> list[NQuad]:
    return parse_rdf(chunk)


# inputs below this skip process startup (tests, tiny loads)
_MP_MIN_BYTES = 1 << 20


def run_bulk(rdf_text: str, out_dir: str, schema_text: str = "",
             n_mappers: int = 4, oracle: Oracle | None = None) -> BulkStats:
    """Map (parallel parse in worker processes) → reduce (uid assignment
    + StoreBuilder finalize) → checkpoint. Returns stats; `out_dir` holds
    the snapshot."""
    t0 = time.perf_counter()
    oracle = oracle or Oracle()
    xm = XidMap(oracle)

    chunks = chunk_lines(rdf_text, n_mappers)
    if n_mappers > 1 and len(rdf_text) >= _MP_MIN_BYTES:
        import sys
        import threading
        # forking a multi-threaded process risks child deadlocks — and
        # jax's runtime threads are C++-level, invisible to
        # threading.active_count(); spawn whenever jax is loaded (a
        # re-import per worker, but safe)
        methods = mp.get_all_start_methods()
        safe_fork = ("fork" in methods
                     and threading.active_count() == 1
                     and "jax" not in sys.modules)
        ctx = mp.get_context("fork" if safe_fork else "spawn")
        with ctx.Pool(processes=min(n_mappers, len(chunks))) as pool:
            parsed: list[list[NQuad]] = pool.map(_map_chunk, chunks)
    else:
        parsed = [parse_rdf(c) for c in chunks]

    schema = parse_schema(schema_text) if schema_text else Schema()
    b = StoreBuilder(schema=schema)
    n = 0
    for batch in parsed:
        for nq in batch:
            n += 1
            s = xm.resolve(nq.subject)
            if nq.object_id is not None:
                b.add_edge(s, nq.predicate, xm.resolve(nq.object_id))
            elif nq.is_star:
                raise ValueError("star deletion invalid in bulk load")
            elif nq.predicate == "dgraph.type":
                b.add_type(s, str(nq.object_value))
            else:
                b.add_value(s, nq.predicate, nq.object_value, nq.lang)
    store = b.finalize()
    os.makedirs(out_dir, exist_ok=True)
    checkpoint.save(store, out_dir, base_ts=0)
    edges = sum(pd.fwd.nnz for pd in store.preds.values()
                if pd.fwd is not None)
    return BulkStats(nquads=n, nodes=store.n_nodes, edges=edges,
                     elapsed_s=time.perf_counter() - t0)


def boot_from(out_dir: str) -> tuple[Store, int]:
    """Load a bulk-produced snapshot (reference: alpha -p dir boot)."""
    return checkpoint.load(out_dir)
