"""External id → uid assignment.

Reference parity: `xidmap/xidmap.go` — a sharded map handing out uids for
blank-node / external ids during loads, backed by Zero's uid leases. Here a
lock-striped dict drawing ranges from `cluster.Oracle.assign_uids` (batch
leases, like the reference's lease chunking).
"""

from __future__ import annotations

from dgraph_tpu.cluster.oracle import Oracle
from dgraph_tpu.utils import locks

LEASE_CHUNK = 1024


class XidMap:
    def __init__(self, oracle: Oracle, shards: int = 16):
        self._oracle = oracle
        self._shards = [
            (locks.make_lock("xidmap.shard"), {}) for _ in range(shards)]
        self._pool_lock = locks.make_lock("xidmap.pool")
        self._pool: list[int] = []
        locks.guarded(self, "xidmap.pool")

    def _lease(self) -> int:
        with self._pool_lock:
            if not self._pool:
                # reversed so pop() hands uids out ASCENDING: monotone
                # allocation keeps ranks append-only, which downstream
                # caches (foreign-tablet adaptation) rely on for validity
                self._pool = list(reversed(
                    self._oracle.assign_uids(LEASE_CHUNK)))
            return self._pool.pop()

    def assign(self, xid: str) -> int:
        """uid for external id, allocating on first sight
        (reference: XidMap.AssignUid)."""
        lock, m = self._shards[hash(xid) % len(self._shards)]
        with lock:
            uid = m.get(xid)
            if uid is None:
                uid = self._lease()
                m[xid] = uid
            return uid

    def resolve(self, ref: str) -> int:
        """Resolve a subject/object reference from a mutation: hex uid
        ("0x1f"), decimal, or external/blank id."""
        if ref.startswith("0x") or ref.startswith("0X"):
            return int(ref, 16)
        if ref.isdigit():
            return int(ref)
        return self.assign(ref)
