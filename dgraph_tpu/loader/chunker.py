"""Mutation input parsing: RDF N-Quads and JSON → NQuad batches.

Reference parity: `chunker/` (`ParseRDF` n-quad lexing into `api.NQuad`,
`ParseJSON` nested-object flattening with blank-node generation). The
subset covers what the reference's live/bulk loaders and mutation API
accept day-to-day: uid/blank subjects, string objects with language tags
and `^^` type hints, star deletion, RDF facet parens, and JSON facets via
the "pred|facet" key convention (index maps for lists).
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass

_TYPE_MAP = {
    "xs:int": int, "xs:integer": int,
    "xs:float": float, "xs:double": float,
    "xs:boolean": lambda s: s.lower() == "true",
    "xs:string": str, "xs:dateTime": str,
}
for _k in list(_TYPE_MAP):
    _TYPE_MAP[f"http://www.w3.org/2001/XMLSchema#{_k.split(':')[1]}"] = _TYPE_MAP[_k]
# vector literal rides as its string form `"[0.1, ...]"`; the schema
# layer (types.parse_vector) decodes it at ingestion
_TYPE_MAP["float32vector"] = str


@dataclass
class NQuad:
    """One parsed statement (reference: api.NQuad)."""

    subject: str                 # "0x1" | "_:blank" | "uid(v)"
    predicate: str
    object_id: str | None = None   # uid-valued object
    object_value: object = None    # scalar-valued object
    lang: str = ""
    is_star: bool = False          # object "*" (delete-all)
    facets: dict | None = None     # (key=value, ...) edge metadata


_NQUAD_RE = re.compile(
    r'^\s*'
    r'(?:<([^>]*)>|(_:[A-Za-z0-9._-]+)|(uid\([^)]*\)))\s+'      # subject
    r'<([^>]*)>\s+'                                             # predicate
    r'(?:'
    r'<([^>]*)>|(_:[A-Za-z0-9._-]+)|(uid\([^)]*\))|(\*)|'       # object id/*
    r'"((?:[^"\\]|\\.)*)"'                                      # literal
    r'(?:@([A-Za-z-]+)|\^\^<([^>]*)>)?'
    r')'
    r'(?:\s*\(([^)]*)\))?'                                      # facets
    r'\s*\.\s*$')


def _parse_facets(spec: str) -> dict:
    """'since=2006-01-02, close=true, score=4' → typed facet dict
    (reference: facets in RDF mutations, chunker/rdf facet parsing)."""
    out: dict = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"facet needs key=value, got {part!r}")
        k, v = part.split("=", 1)
        k, v = k.strip(), v.strip()
        if v.startswith('"') and v.endswith('"'):
            out[k] = v[1:-1]
        elif v in ("true", "false"):
            out[k] = v == "true"
        else:
            try:
                out[k] = int(v)
            except ValueError:
                try:
                    out[k] = float(v)
                except ValueError:
                    out[k] = v
    return out


def parse_rdf(text: str) -> list[NQuad]:
    """Parse N-Quad lines (reference: chunker/rdf parsing)."""
    out: list[NQuad] = []
    for lineno, line in enumerate(text.splitlines(), 1):
        s = line.strip()
        if not s or s.startswith("#"):
            continue
        m = _NQUAD_RE.match(s)
        if not m:
            raise ValueError(f"bad N-Quad at line {lineno}: {line!r}")
        (s_iri, s_blank, s_var, pred, o_iri, o_blank, o_var, star,
         lit, lang, typ, facet_spec) = m.groups()
        subject = s_iri or s_blank or s_var
        nq = NQuad(subject=subject, predicate=pred)
        if facet_spec is not None:
            nq.facets = _parse_facets(facet_spec)
        if star:
            nq.is_star = True
        elif lit is not None:
            v: object = re.sub(r'\\(.)', r'\1', lit)
            if typ:
                conv = _TYPE_MAP.get(typ)
                if conv is None:
                    raise ValueError(f"unknown datatype {typ!r} line {lineno}")
                v = conv(v)
            nq.object_value = v
            nq.lang = lang or ""
        else:
            nq.object_id = o_iri or o_blank or o_var
        out.append(nq)
    return out


def parse_json(obj, _counter: list | None = None) -> list[NQuad]:
    """Flatten a JSON mutation object (reference: chunker/json.go).

    Nested objects without "uid" become blank nodes; lists fan out; keys
    "uid" and "dgraph.type" follow reference semantics.
    """
    if isinstance(obj, (str, bytes)):
        obj = json.loads(obj)
    else:
        import copy
        obj = copy.deepcopy(obj)  # blank-node refs are injected into the
        # tree during flattening; never mutate the caller's object
    counter = _counter if _counter is not None else [0]
    out: list[NQuad] = []
    items = obj if isinstance(obj, list) else [obj]
    for it in items:
        _flatten(it, counter, out)
    return out


def _node_ref(it: dict, counter: list) -> str:
    uid = it.get("uid")
    if uid is None:
        counter[0] += 1
        uid = f"_:json.{counter[0]}"
        it["uid"] = uid
    return str(uid)


def _pop_facets(it: dict) -> dict[str, dict]:
    """Extract "pred|facet" keys (reference: chunker/json.go facet
    convention) → {pred: {facet: value}}. Scalar facets sit beside the
    value key in the SAME object; edge facets sit inside the CHILD
    object, keyed by the edge predicate."""
    fac: dict[str, dict] = {}
    for k in [k for k in it if "|" in k]:
        pred, _, fkey = k.partition("|")
        if pred and fkey:
            fac.setdefault(pred, {})[fkey] = it.pop(k)
    return fac


def _facets_at(fac_entry: dict | None, idx: int) -> dict | None:
    """Resolve a parent-level facet entry for list element `idx`:
    {"0": v, "1": w} index maps pick per element (reference:
    chunker/json.go list-facet convention); plain values apply to every
    element."""
    if not fac_entry:
        return None
    out = {}
    for fkey, v in fac_entry.items():
        if (isinstance(v, dict) and v
                and all(isinstance(x, str) and x.isdigit() for x in v)):
            if str(idx) in v:
                out[fkey] = v[str(idx)]
        else:
            out[fkey] = v
    return out or None


def _flatten(it: dict, counter: list, out: list[NQuad]) -> None:
    subj = _node_ref(it, counter)
    fac = _pop_facets(it)
    for k, v in list(it.items()):
        if k == "uid":
            continue
        vals = v if isinstance(v, list) else [v]
        for idx, one in enumerate(vals):
            if isinstance(one, dict):
                ref = _node_ref(one, counter)
                # edge facets: parent-level "k|facet" (index-mapped for
                # lists) merged with keys inside the child object under
                # the edge predicate's name — child-internal wins; the
                # child's OWN scalar facets stay for its _flatten pass
                edge_fac = _facets_at(fac.get(k), idx) or {}
                for fk in [fk for fk in one
                           if fk.startswith(k + "|")]:
                    edge_fac[fk.partition("|")[2]] = one.pop(fk)
                out.append(NQuad(subject=subj, predicate=k,
                                 object_id=ref,
                                 facets=edge_fac or None))
                _flatten(one, counter, out)
            elif one is None:
                out.append(NQuad(subject=subj, predicate=k, is_star=True))
            else:
                out.append(NQuad(subject=subj, predicate=k,
                                 object_value=one,
                                 facets=_facets_at(fac.get(k), idx)))
