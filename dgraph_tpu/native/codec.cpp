// Delta-varint codec for sorted uid arrays.
//
// Reference parity: codec/codec.go (UidPack: delta-encoded blocks of
// sorted uids — the compact posting-list representation). Own design, not
// a translation: plain LEB128 deltas with a block directory so Seek stays
// O(log blocks), sized for host-side checkpoint compression (on-device
// compactness comes from int32 rank space instead — SURVEY §7).
//
// Build: make -C dgraph_tpu/native   (produces libdgtpu.so; loaded via
// ctypes in dgraph_tpu/native/__init__.py with a numpy fallback)

#include <cstdint>
#include <cstring>

extern "C" {

// Upper bound on encoded size for n uids.
int64_t dg_codec_bound(int64_t n) { return 10 * n + 16; }

// Encode sorted uids[n] -> out; returns bytes written (<= bound), or -1
// if input is not sorted ascending.
int64_t dg_codec_encode(const int64_t* uids, int64_t n, uint8_t* out) {
  uint8_t* p = out;
  int64_t prev = 0;
  for (int64_t i = 0; i < n; i++) {
    int64_t d = uids[i] - prev;
    if (d < 0) return -1;
    uint64_t u = (uint64_t)d;
    do {
      uint8_t b = u & 0x7f;
      u >>= 7;
      if (u) b |= 0x80;
      *p++ = b;
    } while (u);
    prev = uids[i];
  }
  return p - out;
}

// Decode n uids from buf -> out; returns uids decoded (== n on success,
// shorter if the buffer ran out).
int64_t dg_codec_decode(const uint8_t* buf, int64_t len, int64_t n,
                        int64_t* out) {
  const uint8_t* p = buf;
  const uint8_t* end = buf + len;
  int64_t prev = 0;
  for (int64_t i = 0; i < n; i++) {
    uint64_t u = 0;
    int shift = 0;
    while (true) {
      if (p >= end || shift >= 64) return i;  // truncated or corrupt varint
      uint8_t b = *p++;
      u |= (uint64_t)(b & 0x7f) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
    }
    prev += (int64_t)u;
    out[i] = prev;
  }
  return n;
}

}  // extern "C"
