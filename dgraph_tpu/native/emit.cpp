// JSON emitter over columnar level trees.
//
// Reference parity: query/outputnode.go (fastJsonNode → ToJson). The
// reference's answer to render cost is a purpose-built byte-tree encoder
// in Go; ours is this: the Python side lowers an executed LevelNode tree
// to flat arrays (per-leaf pre-encoded JSON fragments aligned to the
// level's rank domain, per-child CSR row maps in domain-position space)
// and this walker emits the response bytes directly — no per-object
// Python allocation on the serving path.
//
// Semantics mirrored from engine/outputnode.py's dict path exactly:
//   - leaves in declaration order, then child edges in order
//   - absent values (empty fragment span) omit the key
//   - empty child lists omit the key; empty objects are dropped from lists
//   - repeated subtrees memoized per (level, domain position)
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <utility>
#include <vector>

struct DgLevel;

struct DgLeaf {
  const uint8_t* key;  // pre-encoded `"name":`
  int64_t key_len;
  int32_t kind;  // 0 = fragment, 1 = uid hex string, 2 = int64
  int32_t pad_;
  const int64_t* frag_off;  // [n+1] blob spans, kind 0 (equal span = absent)
  const uint8_t* frag_blob;
  const int64_t* nums;  // [n], kind 1/2
};

struct DgChild {
  const uint8_t* key;
  int64_t key_len;
  const DgLevel* level;
  const int64_t* row_indptr;  // [parent n + 1]
  const int32_t* row_child;   // positions into child level's domain
};

struct DgLevel {
  int64_t n;  // domain size
  int64_t n_leaves;
  const DgLeaf* leaves;
  int64_t n_children;
  const DgChild* children;
  int64_t level_id;  // dense index for the memo workspace
};

namespace {

struct Emitter {
  std::string out;
  // per level: domain position -> (start, len) of its emitted bytes
  std::vector<std::vector<std::pair<int64_t, int64_t>>> memo;

  void append_span(int64_t start, int64_t len) {
    size_t old = out.size();
    out.resize(old + len);
    memmove(&out[old], &out[start], len);
  }

  void emit_obj(const DgLevel* lv, int64_t p) {
    auto& m = memo[lv->level_id];
    if ((int64_t)m.size() < lv->n) m.assign(lv->n, {0, 0});
    if (m[p].second) {
      append_span(m[p].first, m[p].second);
      return;
    }
    int64_t start = out.size();
    out.push_back('{');
    bool first = true;
    for (int64_t i = 0; i < lv->n_leaves; ++i) {
      const DgLeaf& lf = lv->leaves[i];
      if (lf.kind == 0) {
        int64_t a = lf.frag_off[p], b = lf.frag_off[p + 1];
        if (b <= a) continue;
        if (!first) out.push_back(',');
        first = false;
        out.append((const char*)lf.key, lf.key_len);
        out.append((const char*)lf.frag_blob + a, b - a);
      } else {
        char buf[32];
        int n;
        if (lf.kind == 1) {
          n = snprintf(buf, sizeof buf, "\"0x%llx\"",
                       (unsigned long long)lf.nums[p]);
        } else {
          n = snprintf(buf, sizeof buf, "%lld", (long long)lf.nums[p]);
        }
        if (!first) out.push_back(',');
        first = false;
        out.append((const char*)lf.key, lf.key_len);
        out.append(buf, n);
      }
    }
    for (int64_t i = 0; i < lv->n_children; ++i) {
      const DgChild& ch = lv->children[i];
      int64_t s = ch.row_indptr[p], e = ch.row_indptr[p + 1];
      if (e <= s) continue;
      int64_t mark = out.size();
      if (!first) out.push_back(',');
      out.append((const char*)ch.key, ch.key_len);
      out.push_back('[');
      bool any = false;
      for (int64_t j = s; j < e; ++j) {
        int64_t cm = out.size();
        if (any) out.push_back(',');
        size_t pre = out.size();
        emit_obj(ch.level, ch.row_child[j]);
        if (out.size() - pre == 2) {
          out.resize(cm);  // "{}": drop the object (and its comma)
        } else {
          any = true;
        }
      }
      if (!any) {
        out.resize(mark);  // every row object was empty: drop the key
      } else {
        out.push_back(']');
        first = false;
      }
    }
    out.push_back('}');
    int64_t len = (int64_t)out.size() - start;
    // never memoize "{}": empty objects get truncated by the caller, so
    // a remembered span would dangle past out.size() once rolled back
    if (len > 2) m[p] = {start, len};
  }
};

}  // namespace

extern "C" int64_t dg_emit_block(const DgLevel* root, const int32_t* display,
                                 int64_t n_display, int64_t n_levels,
                                 uint8_t** out_buf) {
  Emitter e;
  e.memo.resize(n_levels);
  e.out.reserve(1 << 16);
  e.out.push_back('[');
  bool any = false;
  for (int64_t i = 0; i < n_display; ++i) {
    int64_t cm = e.out.size();
    if (any) e.out.push_back(',');
    size_t pre = e.out.size();
    e.emit_obj(root, display[i]);
    if (e.out.size() - pre == 2) {
      e.out.resize(cm);
    } else {
      any = true;
    }
  }
  e.out.push_back(']');
  uint8_t* buf = (uint8_t*)malloc(e.out.size());
  if (!buf) return -1;
  memcpy(buf, e.out.data(), e.out.size());
  *out_buf = buf;
  return (int64_t)e.out.size();
}

extern "C" void dg_emit_free(uint8_t* p) { free(p); }
