// CSR construction from edge pairs: the bulk-load reduce hot loop.
//
// Reference parity: dgraph/cmd/bulk/reduce.go (sort shard, dedupe, emit
// packed posting lists) — here emit CSR (indptr/indices) over rank space,
// the layout HBM wants (SURVEY §7). Pairs pack into one uint64 so the
// sort is a single std::sort over flat memory.
//
// Build: make -C dgraph_tpu/native

#include <algorithm>
#include <cstdint>

extern "C" {

// Build CSR from rank pairs (src[i], dst[i]), 0 <= rank < n < 2^31.
// indptr must hold n+1 int32; indices must hold nnz int32 (nnz <= m).
// Returns deduped edge count (nnz), or -1 on bad input.
int64_t dg_build_csr(const int32_t* src, const int32_t* dst, int64_t m,
                     int32_t n, int32_t* indptr, int32_t* indices,
                     uint64_t* scratch /* m u64 */) {
  for (int64_t i = 0; i < m; i++) {
    if (src[i] < 0 || src[i] >= n || dst[i] < 0 || dst[i] >= n) return -1;
    scratch[i] = ((uint64_t)(uint32_t)src[i] << 32) | (uint32_t)dst[i];
  }
  std::sort(scratch, scratch + m);
  int64_t nnz = 0;
  for (int64_t i = 0; i < m; i++) {
    if (i && scratch[i] == scratch[i - 1]) continue;
    scratch[nnz++] = scratch[i];
  }
  for (int32_t r = 0; r <= n; r++) indptr[r] = 0;
  for (int64_t i = 0; i < nnz; i++) {
    indices[i] = (int32_t)(scratch[i] & 0xffffffffu);
    indptr[(scratch[i] >> 32) + 1]++;
  }
  for (int32_t r = 0; r < n; r++) indptr[r + 1] += indptr[r];
  return nnz;
}

}  // extern "C"
