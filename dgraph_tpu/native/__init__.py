"""Native host runtime: ctypes bindings over libdgtpu.so.

Reference parity note (SURVEY §2.6): the reference is pure Go — its
performance-critical host loops are `codec/` varint decode and the bulk
reducer's sort. Those two roles are implemented here in C++ (codec.cpp,
csr.cpp), built with `make -C dgraph_tpu/native`, loaded via ctypes (no
pybind11 in this image). Every entry point has a numpy fallback so the
framework runs without the native build; `HAVE_NATIVE` reports which path
is active.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.dirname(__file__)
_SO = os.path.join(_DIR, "libdgtpu.so")
_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_SO):
        return None
    lib = ctypes.CDLL(_SO)
    lib.dg_codec_bound.restype = ctypes.c_int64
    lib.dg_codec_bound.argtypes = [ctypes.c_int64]
    lib.dg_codec_encode.restype = ctypes.c_int64
    lib.dg_codec_encode.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8)]
    lib.dg_codec_decode.restype = ctypes.c_int64
    lib.dg_codec_decode.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64)]
    lib.dg_build_csr.restype = ctypes.c_int64
    lib.dg_build_csr.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint64)]
    if hasattr(lib, "dg_emit_block"):  # older .so builds predate the emitter
        lib.dg_emit_block.restype = ctypes.c_int64
        lib.dg_emit_block.argtypes = [
            ctypes.POINTER(DgLevel), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
        lib.dg_emit_free.restype = None
        lib.dg_emit_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    _lib = lib
    return lib


def build(quiet: bool = True) -> bool:
    """Compile libdgtpu.so in place (reference role: `go build`)."""
    global _lib, HAVE_NATIVE, HAVE_EMIT
    try:
        subprocess.run(["make", "-C", _DIR],
                       capture_output=quiet, check=True, timeout=120)
    except Exception:
        return False
    _lib = None
    HAVE_NATIVE = _load() is not None
    HAVE_EMIT = HAVE_NATIVE and hasattr(_lib, "dg_emit_block")
    return HAVE_NATIVE


class DgLeaf(ctypes.Structure):
    """Mirrors emit.cpp DgLeaf (a pre-encoded column of one JSON key)."""
    _fields_ = [
        ("key", ctypes.c_void_p), ("key_len", ctypes.c_int64),
        ("kind", ctypes.c_int32), ("pad_", ctypes.c_int32),
        ("frag_off", ctypes.c_void_p), ("frag_blob", ctypes.c_void_p),
        ("nums", ctypes.c_void_p),
    ]


class DgLevel(ctypes.Structure):
    pass


class DgChild(ctypes.Structure):
    """Mirrors emit.cpp DgChild (one uid edge: key + CSR row map)."""
    _fields_ = [
        ("key", ctypes.c_void_p), ("key_len", ctypes.c_int64),
        ("level", ctypes.POINTER(DgLevel)),
        ("row_indptr", ctypes.c_void_p), ("row_child", ctypes.c_void_p),
    ]


DgLevel._fields_ = [
    ("n", ctypes.c_int64),
    ("n_leaves", ctypes.c_int64), ("leaves", ctypes.POINTER(DgLeaf)),
    ("n_children", ctypes.c_int64), ("children", ctypes.POINTER(DgChild)),
    ("level_id", ctypes.c_int64),
]


def emit_block(root: DgLevel, display: np.ndarray, n_levels: int) -> bytes:
    """Emit one block's JSON array from a lowered level tree.

    `display`: int32 domain positions to render at the root. The caller
    keeps every referenced numpy array / bytes object alive for the call.
    """
    lib = _load()
    display = np.ascontiguousarray(display, np.int32)
    out = ctypes.POINTER(ctypes.c_uint8)()
    n = lib.dg_emit_block(ctypes.byref(root), _ptr(display, ctypes.c_int32),
                          len(display), n_levels, ctypes.byref(out))
    if n < 0:
        raise MemoryError("dg_emit_block allocation failed")
    try:
        return ctypes.string_at(out, n)
    finally:
        lib.dg_emit_free(out)


HAVE_NATIVE = _load() is not None
HAVE_EMIT = HAVE_NATIVE and hasattr(_lib, "dg_emit_block")


def _ptr(a: np.ndarray, ct):
    return a.ctypes.data_as(ctypes.POINTER(ct))


# -- codec (reference: codec.Encoder/Decoder) --------------------------------

def codec_encode(uids: np.ndarray) -> bytes:
    """Sorted int64 uids → delta-varint bytes."""
    uids = np.ascontiguousarray(uids, np.int64)
    lib = _load()
    if lib is not None:
        out = np.empty(int(lib.dg_codec_bound(len(uids))), np.uint8)
        n = lib.dg_codec_encode(_ptr(uids, ctypes.c_int64), len(uids),
                                _ptr(out, ctypes.c_uint8))
        if n < 0:
            raise ValueError("uids not sorted ascending")
        return out[:n].tobytes()
    # python fallback: LEB128 deltas
    if len(uids) and (uids[0] < 0 or np.any(np.diff(uids) < 0)):
        raise ValueError("uids not sorted ascending (and nonnegative)")
    out = bytearray()
    prev = 0
    for v in uids.tolist():
        d = v - prev
        prev = v
        while True:
            b = d & 0x7F
            d >>= 7
            if d:
                out.append(b | 0x80)
            else:
                out.append(b)
                break
    return bytes(out)


def codec_decode(buf: bytes, n: int) -> np.ndarray:
    """delta-varint bytes → sorted int64 uids[n]."""
    lib = _load()
    if lib is not None:
        raw = np.frombuffer(buf, np.uint8)
        out = np.empty(n, np.int64)
        got = lib.dg_codec_decode(_ptr(raw, ctypes.c_uint8), len(raw), n,
                                  _ptr(out, ctypes.c_int64))
        if got != n:
            raise ValueError(f"decoded {got} of {n} uids")
        return out
    out = np.empty(n, np.int64)
    prev = 0
    pos = 0
    for i in range(n):
        u = 0
        shift = 0
        while True:
            if pos >= len(buf):
                raise ValueError(f"decoded {i} of {n} uids")
            b = buf[pos]
            pos += 1
            u |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        prev += u
        out[i] = prev
    return out


# -- CSR build (reference: bulk reduce) --------------------------------------

def build_csr(src: np.ndarray, dst: np.ndarray, n: int):
    """Edge pairs → (indptr[int32, n+1], indices[int32, nnz]), sorted rows,
    deduped. Matches store._csr_from_pairs output exactly."""
    src = np.ascontiguousarray(src, np.int32)
    dst = np.ascontiguousarray(dst, np.int32)
    m = len(src)
    lib = _load()
    if lib is not None and m:
        indptr = np.empty(n + 1, np.int32)
        indices = np.empty(m, np.int32)
        scratch = np.empty(m, np.uint64)
        nnz = lib.dg_build_csr(
            _ptr(src, ctypes.c_int32), _ptr(dst, ctypes.c_int32), m, n,
            _ptr(indptr, ctypes.c_int32), _ptr(indices, ctypes.c_int32),
            _ptr(scratch, ctypes.c_uint64))
        if nnz < 0:
            raise ValueError("rank out of range in edge pairs")
        return indptr, indices[:nnz].copy()
    from dgraph_tpu.store.store import _csr_from_pairs_np
    rel = _csr_from_pairs_np(src, dst, n)
    return rel.indptr, rel.indices
