"""Fused level kernel: expand → filter → paginate → dedupe as ONE program.

Reference parity: one level of `query.SubGraph.ProcessGraph` —
posting-list expansion (worker/task.go processTask), filter intersection
(algo.IntersectSorted over the filter SubGraph's result), and per-row
pagination (first/offset applied to each UidMatrix row) — which the
reference runs as separate Go passes with heap merges in between. Here the
whole level body is a single jitted program: the only host work left for a
filtered, paginated hop is evaluating the filter tree to a sorted
`allowed` set (index lookups) and reading back the compacted result.

Row pagination on device: after the keep-mask (validity ∧ filter), each
edge's within-row rank among SURVIVORS is a segment-local exclusive
cumsum; first/offset become rank-window comparisons, including the
negative-first (last k) form via per-row survivor totals.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from dgraph_tpu.ops.hop import gather_edges
from dgraph_tpu.ops.uidalgebra import _member, sentinel, sort_unique_count

NO_LIMIT = (1 << 30)


def filter_paginate(nbrs, seg, edge_pos, valid, allowed, offset, first,
                    n_rows: int, use_allowed: bool):
    """The filter+paginate+compact body shared by the single-device fused
    level and its per-shard SPMD form (parallel/dhop.py matrix_level).
    Inputs are one device's gathered edge slots; `seg` must be
    nondecreasing (CSR row order). Returns (nbrs, seg, pos, n_kept) with
    kept edges compacted to the front in row order."""
    edge_cap = nbrs.shape[0]
    keep = valid
    if use_allowed:
        keep = keep & _member(nbrs, allowed)

    # within-row survivor rank: exclusive segment-local cumsum of `keep`
    ksum = jnp.cumsum(keep.astype(jnp.int32))
    excl = ksum - keep.astype(jnp.int32)        # exclusive at j
    # survivors before each row start (segment base)
    row_ids = jnp.arange(n_rows, dtype=jnp.int32)
    # first edge slot of each row: searchsorted over seg (seg nondecreasing)
    row_start = jnp.searchsorted(seg, row_ids, side="left")
    row_end = jnp.searchsorted(seg, row_ids, side="right")
    base_at_row = jnp.take(excl, jnp.minimum(row_start, edge_cap - 1),
                           mode="clip")
    base_at_row = jnp.where(row_start < edge_cap, base_at_row, 0)
    end_ksum = jnp.take(ksum, jnp.maximum(row_end - 1, 0), mode="clip")
    end_ksum = jnp.where(row_end > 0, end_ksum, 0)
    row_total = jnp.maximum(end_ksum - base_at_row, 0)  # survivors per row

    safe_seg = jnp.clip(seg, 0, n_rows - 1)
    rank = excl - base_at_row[safe_seg]         # within-row survivor rank
    lo = offset
    k = jnp.where(first == NO_LIMIT, jnp.int32(NO_LIMIT), first)
    hi = jnp.where(k >= 0, lo + k, jnp.int32(NO_LIMIT))
    paged = keep & (rank >= lo) & (rank < hi)
    # negative first: last |k| of the post-offset window
    neg = (k < 0)
    tail_lo = jnp.maximum(row_total[safe_seg] + k, lo)
    paged = jnp.where(neg, keep & (rank >= tail_lo), paged)

    snt = sentinel(nbrs.dtype)
    m_nbrs = jnp.where(paged, nbrs, snt)
    m_seg = jnp.where(paged, seg, jnp.int32(2**31 - 1))
    m_pos = jnp.where(paged, edge_pos, 0)
    # compact kept edges to the front, preserving CSR row order (slots are
    # already ordered by (seg, within-row)); stable order under sort of
    # slot keys: use the slot index where paged, else edge_cap
    slot_key = jnp.where(paged, jnp.arange(edge_cap, dtype=jnp.int32),
                         jnp.int32(edge_cap))
    order = jnp.argsort(slot_key)
    n_kept = jnp.sum(paged.astype(jnp.int32))
    return m_nbrs[order], m_seg[order], m_pos[order], n_kept, m_nbrs


@functools.partial(jax.jit, static_argnames=("edge_cap", "out_cap",
                                             "use_allowed"))
def expand_level(indptr: jax.Array, indices: jax.Array, frontier: jax.Array,
                 allowed: jax.Array, offset, first,
                 edge_cap: int, out_cap: int, use_allowed: bool):
    """One child level, fused.

    Args:
      frontier   [f_cap] sorted sentinel-padded ranks
      allowed    [a_cap] sorted sentinel-padded filter set (ignored unless
                 use_allowed — pass a dummy 1-element array then)
      offset     int32: per-row survivors to skip
      first      int32: >0 keep first k after offset; <0 keep last k;
                 NO_LIMIT = unpaginated
      edge_cap/out_cap: static buckets (overflow contract as ops.hop)

    Returns (nbrs[edge_cap], seg[edge_cap], pos[edge_cap], n_kept,
             next_frontier[out_cap], n_unique, total_edges):
      the kept edges compacted to the front in CSR row order, their
      frontier segments and absolute facet positions, plus the deduped
      next frontier. Valid only if total_edges <= edge_cap and
      n_unique <= out_cap.
    """
    nbrs, seg, edge_pos, valid, total = gather_edges(
        indptr, indices, frontier, edge_cap)
    c_nbrs, c_seg, c_pos, n_kept, m_nbrs = filter_paginate(
        nbrs, seg, edge_pos, valid, allowed, offset, first,
        frontier.shape[0], use_allowed)
    nxt, n_unique = sort_unique_count(m_nbrs, out_cap)
    return c_nbrs, c_seg, c_pos, n_kept, nxt, n_unique, total
