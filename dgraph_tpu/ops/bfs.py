"""Batched traversal over lane-packed frontier bitmaps — the throughput path.

Reference parity: the reference serves concurrent queries with goroutines,
each walking posting lists independently (worker/task.go, one goroutine per
`ProcessTaskOverNetwork`; LDBC SNB IC mixes in BASELINE.json run many
queries at once). The TPU-native equivalent batches B concurrent traversals
into the *lanes* of a dense frontier bitmap:

    mask[n_nodes, B] int8      mask[v, q] = 1 iff node v is in query q's set

One hop for ALL queries is two wide array ops over the COO edge list:

    active  = mask[src]                  row-gather   [E, B]
    next    = zeros.at[dst].max(active)  row-scatter  [N, B]

The point is access *width*: TPU random gather/scatter costs are bounded by
access count, not bytes (measured ~8 ns/access on v5e regardless of row
width), so widening each access to a B-byte lane row amortises the
irregular-memory tax across B queries — the same shape the reference can't
reach because its per-query goroutines share nothing.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ranks_to_bitmap", "bitmap_to_ranks", "bitmap_hop",
           "bitmap_recurse", "EllGraph", "build_ell", "ell_recurse",
           "DeviceEll", "device_ell", "prepare_parts", "make_ell_recurse",
           "make_ell_step", "make_ell_count", "make_ell_tree",
           "pack_seed_masks", "unpack_masks"]


def ranks_to_bitmap(rank_lists, n_nodes: int) -> jnp.ndarray:
    """Host helper: B rank lists → [n_nodes, B] int8 frontier bitmap."""
    import numpy as np
    out = np.zeros((n_nodes, len(rank_lists)), np.int8)
    for q, ranks in enumerate(rank_lists):
        out[np.asarray(ranks, np.int64), q] = 1
    return out


def bitmap_to_ranks(mask) -> list:
    """Host helper: [n_nodes, B] bitmap → list of B sorted rank arrays."""
    import numpy as np
    m = np.asarray(mask)
    return [np.nonzero(m[:, q])[0].astype(np.int32)
            for q in range(m.shape[1])]


@jax.jit
def bitmap_hop(src: jax.Array, dst: jax.Array, mask: jax.Array) -> jax.Array:
    """One hop of B concurrent traversals: next[v,q] = OR over edges u→v of
    mask[u,q]. `src`/`dst` are the COO edge list ([E] int32, any order)."""
    active = jnp.take(mask, src, axis=0, mode="clip")
    return jnp.zeros_like(mask).at[dst].max(active, mode="drop")


@functools.partial(jax.jit, static_argnames=("depth",))
def bitmap_recurse(src: jax.Array, dst: jax.Array, deg: jax.Array,
                   mask0: jax.Array, depth: int):
    """Depth-bounded loop=false @recurse for B queries at once, fully fused.

    `deg[n_nodes] int32` is the out-degree vector (for edge counting);
    `mask0[n_nodes, B] int8` holds each query's seed set. Returns
    `(last[n,B], seen[n,B], edges[B] int32)` where `seen` is each query's
    visited set (reference: expandRecurse's seen map per query) and
    `edges[q]` counts edges traversed from every expanded frontier — the
    north-star counter.
    """
    degf = deg.astype(jnp.float32)

    def hop(carry, _):
        frontier, seen, edges = carry
        # per-query frontier out-degree sum — one MXU matvec
        hop_edges = degf @ frontier.astype(jnp.float32)
        edges = edges + hop_edges.astype(jnp.int32)
        nxt = bitmap_hop(src, dst, frontier)
        fresh = jnp.where(seen > 0, jnp.int8(0), nxt)
        seen = jnp.maximum(seen, fresh)
        return (fresh, seen, edges), None

    B = mask0.shape[1]
    (last, seen, edges), _ = lax.scan(
        hop, (mask0, mask0, jnp.zeros((B,), jnp.int32)), None, length=depth)
    return last, seen, edges


# ---------------------------------------------------------------------------
# ELL pull-hop: the access-amortised form of the batched traversal.
#
# The push kernel above pays one random row-gather AND one random
# row-scatter per edge. Measured on v5e, random row access costs ~10 ns
# REGARDLESS of row width, so the winning shape is: (1) eliminate the
# scatter entirely by pulling over in-neighbor lists, and (2) amortise each
# access over as many concurrent queries as fit in the row (bit-packed
# lanes: W words = word_bits·W queries per access). One hop is then pure
# gathers + bitwise ORs — no scatter, no sort, fully static shapes.
#
# Layout (PR 7, FeatGraph-style degree buckets): nodes are RENUMBERED by
# in-degree class so each class's output is a contiguous slice and the
# next-frontier mask is rebuilt by concatenation, not scatter. Two kernel
# templates:
#   * dense-lane ELL for the low-degree body (indeg ≤ SEG_MIN_DEG): one
#     [n_b, K] int32 block per EXACT degree K — zero padding — evaluated
#     as an unrolled gather-OR chain (fuses into one pass on CPU, one
#     VMEM-resident loop on TPU);
#   * segment-CSR for the heavy tail (indeg > SEG_MIN_DEG): neighbor
#     lists split into SEG_TILE-wide tiles ([M, SEG_TILE] int32, padded
#     only in each row's LAST tile), tile partials OR-reduced, then a
#     tiny second-level gather combines each heavy row's tiles (rows
#     bucketed by power-of-two tile count).
# Padding is bounded by SEG_TILE-1 slots per heavy row instead of the old
# power-of-4 ladder's up-to-4x blowup (BENCH r05: 58% of device edges
# were ELL padding; this layout measures <5% on the same graph).
# Reference: this plays codec/'s role of making posting data compact AND
# the UidPack role of block iteration — shaped for the MXU/VPU.

SEG_MIN_DEG = 32      # dense-lane ELL up to this in-degree; heavier → tiles
SEG_TILE = 8          # segment-CSR tile width (max padding per heavy row)
CHAIN_MAX = 32        # widest unrolled gather-OR chain; beyond → reduce


@dataclass
class EllGraph:
    """Degree-bucketed in-neighbor blocks over a permuted rank space.

    `parts` lists the dense-lane blocks in permuted row order:
    ("zero", None, rows) for the indeg-0 class, ("ell", [rows, K] int32,
    rows) per present degree K ≤ seg_min. `tiles`/`lvl2` hold the heavy
    tail's segment-CSR (tile matrix + per-tile-count combine indices);
    heavy rows sit after all dense rows in the permutation."""

    n: int                                  # node count
    parts: list                             # dense blocks, permuted order
    tiles: object                           # [M, seg_tile] int32 | None
    lvl2: list                              # [h_b, K2] int32 tile combines
    seg_rows: int                           # heavy (tail) row count
    outdeg: object                          # [n] f32, permuted space
    perm_order: object                      # new rank -> old rank
    new_of_old: object                      # old rank -> new rank
    ks: list = field(default_factory=list)  # dense widths present

    @property
    def nnz(self) -> int:
        return int(self.outdeg.sum())

    @property
    def padded_edges(self) -> int:
        """Total level-1 gather slots (real edges + padding) — the device
        edge traffic per hop; `ell_padding_ratio` derives from it."""
        dense = sum(int(e.size) for kind, e, _ in self.parts
                    if kind == "ell")
        return dense + (int(self.tiles.size) if self.tiles is not None
                        else 0)


def build_ell(indptr, indices, seg_min: int = SEG_MIN_DEG,
              seg_tile: int = SEG_TILE) -> EllGraph:
    """Build the bucketed ELL + segment-CSR blocks from a CSR relation.

    Host-side, once per (snapshot, predicate, direction) — every array is
    produced by whole-graph vectorized passes (one stable argsort for the
    CSR transpose plus O(E) fills), not per-node Python loops: the PR-7
    rewrite took the 1M-node bench build from ~9 s to ~4 s, and the
    amortization story (engine/batch plan + ELL caches) makes even that a
    once-per-snapshot cost."""
    import numpy as np

    n = indptr.shape[0] - 1
    deg_out = np.diff(indptr).astype(np.int64)
    src = np.repeat(np.arange(n, dtype=np.int32), deg_out)
    # CSR transpose: in-neighbors grouped by destination, sources
    # ascending within each group (stable sort keeps src order)
    order = np.argsort(indices, kind="stable")
    csrc = src[order]
    indeg = (np.bincount(indices, minlength=n).astype(np.int64) if n
             else np.zeros(0, np.int64))
    cindptr = np.concatenate([[0], np.cumsum(indeg)])

    small = indeg <= seg_min
    ks = sorted(int(k) for k in np.unique(indeg[small])) if n else [0]
    bucket = np.full(n, len(ks), np.int64)
    bucket[small] = np.searchsorted(np.array(ks), indeg[small])
    heavy = ~small
    ntiles = np.zeros(n, np.int64)
    ntiles[heavy] = -(-indeg[heavy] // seg_tile)
    # permutation: dense degree classes ascending, then the heavy tail by
    # tile count; first-neighbor secondary order gives consecutive rows
    # nearby gather targets (cache-line sharing on CPU, DMA locality on
    # TPU) at zero extra cost
    first_nbr = np.full(n, n, np.int64)
    nz = indeg > 0
    first_nbr[nz] = csrc[cindptr[:-1][nz]]
    sort_key = np.where(heavy, len(ks) + ntiles, bucket)
    perm_order = np.lexsort((first_nbr, sort_key))
    new_of_old = np.empty(n, np.int64)
    new_of_old[perm_order] = np.arange(n)
    cnew = new_of_old[csrc] if len(csrc) else csrc.astype(np.int64)

    def fill_rows(nodes, K):
        """[len(nodes), K] in-neighbor block (pad=n), one vector pass."""
        nb = np.full((len(nodes), K), n, np.int32)
        deg = indeg[nodes]
        total = int(deg.sum())
        if total:
            cum = np.cumsum(deg)
            base = np.repeat(cum - deg, deg)
            ar = np.arange(total)
            flat = np.repeat(cindptr[nodes], deg) + ar - base
            nb[np.repeat(np.arange(len(nodes)), deg), ar - base] = \
                cnew[flat]
        return nb

    counts = np.bincount(bucket, minlength=len(ks) + 1)
    parts = []
    off = 0
    for i, K in enumerate(ks):
        nodes = perm_order[off:off + counts[i]]
        off += counts[i]
        if K == 0:
            parts.append(("zero", None, len(nodes)))
        else:
            parts.append(("ell", fill_rows(nodes, K), len(nodes)))
    heavy_nodes = perm_order[off:]
    seg_rows = len(heavy_nodes)
    tiles = None
    lvl2 = []
    if seg_rows:
        hdeg = indeg[heavy_nodes]
        hnt = -(-hdeg // seg_tile)
        M = int(hnt.sum())
        tiles = np.full((M, seg_tile), n, np.int32)
        total = int(hdeg.sum())
        cum = np.cumsum(hdeg)
        base = np.repeat(cum - hdeg, hdeg)
        ar = np.arange(total)
        within = ar - base
        tile_start = np.concatenate([[0], np.cumsum(hnt)])[:-1]
        flat = np.repeat(cindptr[heavy_nodes], hdeg) + within
        slot = np.repeat(tile_start * seg_tile, hdeg) + within
        tiles[slot // seg_tile, slot % seg_tile] = cnew[flat]
        # second level: combine each heavy row's tile partials; rows are
        # already ntile-sorted, so power-of-two buckets are contiguous
        k2s = sorted(set(int(1 << max(int(t - 1).bit_length(), 0))
                         for t in np.unique(hnt)))
        b2 = np.searchsorted(np.array(k2s), hnt)
        c2 = np.bincount(b2, minlength=len(k2s))
        off2 = 0
        for i, K2 in enumerate(k2s):
            rows = np.arange(off2, off2 + c2[i])
            off2 += c2[i]
            t2 = np.full((len(rows), K2), M, np.int32)  # M = zero partial
            d2 = hnt[rows]
            tot2 = int(d2.sum())
            if tot2:
                cum2 = np.cumsum(d2)
                base2 = np.repeat(cum2 - d2, d2)
                ar2 = np.arange(tot2)
                t2[np.repeat(np.arange(len(rows)), d2), ar2 - base2] = \
                    np.repeat(tile_start[rows], d2) + ar2 - base2
            lvl2.append(t2)
    return EllGraph(n=n, parts=parts, tiles=tiles, lvl2=lvl2,
                    seg_rows=seg_rows,
                    outdeg=deg_out[perm_order].astype(np.float32),
                    perm_order=perm_order, new_of_old=new_of_old, ks=ks)


def pack_seed_masks(g: EllGraph, rank_lists,
                    word_bits: int = 32) -> "jnp.ndarray":
    """B seed rank lists (OLD rank space) → [n+1, B/word_bits] packed mask
    in the permuted space, sentinel zero row last. B must be a multiple of
    `word_bits` (32 for the serving default, 64 for the x64 bench path)."""
    import numpy as np
    B = len(rank_lists)
    assert B % word_bits == 0, "lane count must pack into mask words"
    dt = np.uint32 if word_bits == 32 else np.uint64
    m = np.zeros((g.n + 1, B // word_bits), dt)
    for q, ranks in enumerate(rank_lists):
        r = g.new_of_old[np.asarray(ranks, np.int64)]
        m[r, q // word_bits] |= dt(1 << (q % word_bits))
    return m


def unpack_masks(g: EllGraph, mask, word_bits: int = 32) -> list:
    """[n+1, W] packed mask → list of B sorted OLD-rank arrays."""
    import numpy as np
    m = np.asarray(mask)[:g.n]
    dt = m.dtype.type
    out = []
    for q in range(m.shape[1] * word_bits):
        rows = np.nonzero(
            (m[:, q // word_bits] >> dt(q % word_bits)) & dt(1))[0]
        out.append(np.sort(g.perm_order[rows]).astype(np.int32))
    return out


# bytes a reduce-form gather may NOMINALLY materialise before row-chunking
# ([rows, K, W] for chains wider than CHAIN_MAX — only the widest lvl2
# combine buckets take this path, and their row counts shrink as K2 grows,
# so chunking is a guard rail for adversarial degree distributions, not a
# tuned path)
GATHER_BUDGET = 12 << 30


@dataclass
class DeviceEll:
    """EllGraph's index arrays resident on device, word-dtype independent
    (indices are int32 whatever the mask word width)."""

    n: int
    parts: list            # ("zero", None, rows) | ("ell", dev, rows)
    tiles: object          # device [M, seg_tile] | None
    lvl2: list             # device [h_b, K2] blocks
    seg_rows: int


def device_ell(g: EllGraph) -> DeviceEll:
    parts = [(kind, jax.device_put(e) if e is not None else None, rows)
             for kind, e, rows in g.parts]
    return DeviceEll(
        n=g.n, parts=parts,
        tiles=jax.device_put(g.tiles) if g.tiles is not None else None,
        lvl2=[jax.device_put(t) for t in g.lvl2], seg_rows=g.seg_rows)


def prepare_parts(dev: DeviceEll, W: int):
    """Pre-shape the device blocks for a hop at lane width W. The XLA
    path uses the blocks as-is (the gather-OR chain never materialises a
    [rows, K, W] intermediate); under DGRAPH_TPU_PALLAS=1 dense blocks
    and the tile matrix are row-padded for the Pallas DMA-ring hop
    (ops/pallas_hop.py) instead."""
    import os
    use_pallas = os.environ.get("DGRAPH_TPU_PALLAS", "") == "1"
    if use_pallas:
        # import only under the flag: the default XLA path must not
        # couple to the experimental pallas namespace
        from dgraph_tpu.ops.pallas_hop import BLOCK_ROWS

    def pad_rows(e):
        n_b = e.shape[0]
        padded = -(-n_b // BLOCK_ROWS) * BLOCK_ROWS
        if padded == n_b:
            return jnp.asarray(e, jnp.int32), n_b
        pad = jnp.full((padded - n_b, e.shape[1]), dev.n, jnp.int32)
        return jnp.concatenate([jnp.asarray(e, jnp.int32), pad]), n_b

    parts = []
    for kind, e, rows in dev.parts:
        if kind == "zero" or rows == 0:
            parts.append(("zero", None, rows))
        elif use_pallas:
            parts.append(("pallas", *pad_rows(e)))
        else:
            parts.append(("chain", e, rows))
    tiles = None
    if dev.tiles is not None and dev.seg_rows:
        if use_pallas and dev.tiles.shape[0]:
            tiles = ("pallas", *pad_rows(dev.tiles))
        else:
            tiles = ("chain", dev.tiles, dev.tiles.shape[0])
    return {"parts": parts, "tiles": tiles, "lvl2": list(dev.lvl2),
            "seg_rows": dev.seg_rows, "n": dev.n}


# Sticky fail-safe: the first bucket_hop_pallas that fails to trace or
# compile flips this and every pallas bucket (this one included) falls
# back to the XLA gather hop — an untested Mosaic compile must degrade
# a perf experiment, never burn the serving path (or a chip window).
_pallas_failed = False


def _chain_or(frontier, e, dtype):
    """out[i] = OR_k frontier[e[i, k]] as an unrolled gather chain —
    XLA fuses the K gathers into one output pass (no [rows, K, W]
    intermediate; measured ~3x the lax.reduce form on the CPU backend).
    Chains wider than CHAIN_MAX fall back to the reduce form, chunked
    when the nominal intermediate would blow GATHER_BUDGET."""
    rows, K = e.shape
    W = frontier.shape[1]
    if K <= CHAIN_MAX:
        acc = frontier[e[:, 0]]
        for k in range(1, K):
            acc = acc | frontier[e[:, k]]
        return acc
    row_bytes = K * W * frontier.dtype.itemsize
    if rows * row_bytes <= GATHER_BUDGET:
        return lax.reduce(frontier[e], dtype(0), lax.bitwise_or, (1,))
    ch = max(1, min(GATHER_BUDGET // row_bytes, rows))
    nch = -(-rows // ch)
    pad = jnp.full((nch * ch - rows, K), frontier.shape[0] - 1, jnp.int32)
    e3 = jnp.concatenate([e, pad]).reshape(nch, ch, K)
    out = lax.map(
        lambda c: lax.reduce(frontier[c], dtype(0), lax.bitwise_or, (1,)),
        e3)
    return out.reshape(-1, W)[:rows]


def _pallas_bucket_part(e, n_b, frontier):
    """One pallas block's hop with XLA-gather fallback. The padded rows
    index frontier's all-zero sentinel row, so the gather form is exact
    on the same padded input."""
    global _pallas_failed
    from dgraph_tpu.utils.metrics import METRICS
    if not _pallas_failed:
        try:
            from dgraph_tpu.ops.pallas_hop import bucket_hop_pallas
            return bucket_hop_pallas(e, frontier)[:n_b]
        except Exception:  # noqa: BLE001 — any trace/compile failure
            _pallas_failed = True
            METRICS.set_gauge("pallas_degraded", 1.0)
            from dgraph_tpu.utils import logging as xlog
            xlog.get("ops").warning(
                "pallas hop failed to trace/compile; falling back to "
                "the XLA gather hop for every bucket (perf experiment "
                "degraded, results unaffected)", exc_info=True)
    # counted per fallback BUCKET TRACE (this body runs at trace time,
    # once per compiled program, not per execution): the sticky
    # degradation stays visible in /debug/prometheus_metrics instead of
    # one log line scrolling away
    METRICS.inc("pallas_fallback_total")
    return lax.reduce(frontier[e], frontier.dtype.type(0),
                      lax.bitwise_or, (1,))[:n_b]


def _ell_hop(prepared, frontier, W, dtype=jnp.uint32):
    """next[v] = OR of frontier[u] over in-neighbors u — gathers only.
    Dense degree classes run as gather-OR chains; the heavy tail runs
    tile partials + the tiny second-level combine; "pallas" blocks ride
    the explicit DMA-ring kernel (ops/pallas_hop.py), falling back to
    the gather if it fails to trace/compile (_pallas_bucket_part)."""
    outs = []
    for kind, e, rows in prepared["parts"]:
        if kind == "zero":
            outs.append(jnp.zeros((rows, W), dtype))
        elif kind == "pallas":
            outs.append(_pallas_bucket_part(e, rows, frontier))
        else:
            outs.append(_chain_or(frontier, e, dtype))
    tiles = prepared["tiles"]
    if tiles is not None:
        tkind, te, trows = tiles
        if tkind == "pallas":
            acc = _pallas_bucket_part(te, trows, frontier)
        else:
            acc = _chain_or(frontier, te, dtype)
        partials = jnp.concatenate([acc, jnp.zeros((1, W), dtype)])
        for t2 in prepared["lvl2"]:
            outs.append(_chain_or(partials, t2, dtype))
    outs.append(jnp.zeros((1, W), dtype))       # sentinel row
    return jnp.concatenate(outs, axis=0)


COUNT_BLK = 1 << 15   # edge-counter node-block rows (bounds unpack memory)


def _count_mask(mask, outdeg_pad, n, W, word_bits):
    """Per-lane out-degree mass of a packed mask: unpack lane bits and
    matvec on the MXU (f32 exact while each lane's TOTAL stays under
    2^24 — the per-run analog of the old per-hop bound; int32 out).
    Blocked over node rows so the unpack never materialises n·B floats."""
    n_pad = outdeg_pad.shape[0]
    nblk = n_pad // COUNT_BLK
    fpad = jnp.concatenate(
        [mask[:n], jnp.zeros((n_pad - n, W), mask.dtype)])
    shifts = jnp.arange(word_bits, dtype=mask.dtype)

    def body(i, acc):
        sl = lax.dynamic_slice_in_dim(fpad, i * COUNT_BLK, COUNT_BLK, 0)
        od = lax.dynamic_slice_in_dim(outdeg_pad, i * COUNT_BLK,
                                      COUNT_BLK, 0)
        bits = ((sl[:, :, None] >> shifts) & mask.dtype.type(1)
                ).astype(jnp.float32).reshape(COUNT_BLK, W * word_bits)
        return acc + od @ bits

    out = lax.fori_loop(0, nblk, body,
                        jnp.zeros((W * word_bits,), jnp.float32))
    return out.astype(jnp.int32)


def make_ell_count(outdeg, n: int, W: int, word_bits: int = 32):
    """Compile the exact per-query edge counter over final masks:
    edges[q] = Σ outdeg[v]·[v ∈ seen \\ last] — every frontier the run
    expanded is exactly `seen` minus the never-expanded last fresh set,
    so ONE matvec replaces the old per-hop accumulation (same integers,
    depth× less unpack traffic)."""
    nblk = -(-max(n, 1) // COUNT_BLK)
    outdeg_pad = jnp.concatenate(
        [jnp.asarray(outdeg),
         jnp.zeros((nblk * COUNT_BLK - n,), jnp.float32)])

    @jax.jit
    def count(last, seen):
        return _count_mask(seen & ~last, outdeg_pad, n, W, word_bits)

    return count


def make_ell_recurse(dev: DeviceEll, outdeg, n: int, W: int,
                     count_edges: bool = True, word_bits: int = 32):
    """Compile a depth-parameterised loop=false @recurse over a DeviceEll
    already resident on device. Returns fn(mask0, depth[, keep_hops]) →
    (last[n+1,W], seen[n+1,W], edges[B] int32[, hops]). The seed mask is
    DONATED: the scan reuses its buffer for the frontier carry instead of
    holding seed + frontier + seen live (callers re-put per launch)."""
    prepared = prepare_parts(dev, W)
    dtype = jnp.uint32 if word_bits == 32 else jnp.uint64
    if count_edges:
        nblk = -(-max(n, 1) // COUNT_BLK)
        outdeg_pad = jnp.concatenate(
            [jnp.asarray(outdeg),
             jnp.zeros((nblk * COUNT_BLK - n,), jnp.float32)])

    @functools.partial(jax.jit, donate_argnums=(0,),
                       static_argnames=("depth", "keep_hops"))
    def recurse(mask0, depth: int, keep_hops: bool = False):
        def hop(carry, _):
            frontier, seen = carry
            nxt = _ell_hop(prepared, frontier, W, dtype)
            fresh = nxt & ~seen
            seen = seen | fresh
            return (fresh, seen), (fresh if keep_hops else None)

        (last, seen), hops = lax.scan(
            hop, (mask0, mask0), None, length=depth)
        if count_edges:
            # exact per-lane counters from the final masks (one matvec;
            # see make_ell_count) — identical integers to the per-hop
            # accumulation because first-visit frontiers partition
            # seen \ last
            edges = _count_mask(seen & ~last, outdeg_pad, n, W, word_bits)
        else:
            edges = jnp.zeros((W * word_bits,), jnp.int32)
        if keep_hops:
            # hops[h] = the FRESH mask after hop h+1 (first-visit sets) —
            # what tree reconstruction needs (engine batch path)
            return last, seen, edges, hops
        return last, seen, edges

    return recurse


def make_ell_step(dev: DeviceEll, n: int, W: int, word_bits: int = 32,
                  first_visit: bool = True):
    """Compile a RESUMABLE hop block: fn(frontier, seen, depth) →
    (frontier', seen', hops[depth, n+1, W]). Both mask carries are
    DONATED — successive blocks of a staged traversal (engine/batch.py's
    shortest groups) hand their buffers forward instead of re-allocating
    per stage, the donation contract the README documents.

    `first_visit=False` drops the seen-masking: hops[h] is then the FULL
    set reachable in exactly h+1 hops (the level-DAG the k-shortest
    enumeration consumes), with `seen` passed through untouched."""
    prepared = prepare_parts(dev, W)
    dtype = jnp.uint32 if word_bits == 32 else jnp.uint64

    @functools.partial(jax.jit, donate_argnums=(0, 1),
                       static_argnames=("depth",))
    def step(frontier, seen, depth: int):
        def hop(carry, _):
            f, s = carry
            nxt = _ell_hop(prepared, f, W, dtype)
            if first_visit:
                fresh = nxt & ~s
                s = s | fresh
            else:
                fresh = nxt
            return (fresh, s), fresh

        (f, s), hops = lax.scan(hop, (frontier, seen), None, length=depth)
        return f, s, hops

    return step


def make_ell_tree(stages, n: int, W: int, word_bits: int = 32):
    """Compile a level-TREE pipeline over lane-packed masks: the batched
    form of a whole nested query (engine/treebatch.py), one fused XLA
    program for B = word_bits·W concurrent queries.

    Reference parity: query/query.go ProcessGraph descends a SubGraph
    tree level by level, one task per child per goroutine; here every
    level of every lane is one stage of this program, and filters are
    bitmask ANDs instead of per-uid IntersectSorted calls.

    All masks live in the STORE's global rank space, shape [n+1, W]
    (row n = sentinel, always zero). Each stage's EllGraph has its own
    degree-class permutation, so a stage translates its parent mask into
    its own permuted space (one row gather), does the ELL pull-hop, and
    translates back (one row gather) — both translations stream
    sequentially and are noise next to the edge gather.

    `stages` is a list of dicts (static structure, device arrays):
      kind      "hop" | "recurse"
      prepared  prepare_parts output for the stage's EllGraph
      perm_in   [n+1] int32 device: permuted row r ← global perm_in[r]
      out_idx   [n+1] int32 device: global row v ← permuted out_idx[v]
      parent    ("seed", slot) | ("stage", idx earlier in the list)
      filt      filter-mask slot index | None  (global space, ANDed in)
      depth     recurse only: hop count (static)
      keep_hops recurse only: also return per-hop first-visit masks

    Returns fn(seeds: tuple, filts: tuple) → tuple with one entry per
    stage: hop → mask [n+1, W]; recurse → seen [n+1, W] (reachable set
    incl. seeds) or (seen, hops [depth, n+1, W]) when keep_hops. The
    seed and filter masks are DONATED (consumed by the first gather).
    """
    dtype = jnp.uint32 if word_bits == 32 else jnp.uint64

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run(seeds, filts):
        outs = []
        results = []
        for s in stages:
            kind, par = s["kind"], s["parent"]
            parent = (seeds[par[1]] if par[0] == "seed"
                      else outs[par[1]])
            filt = filts[s["filt"]] if s["filt"] is not None else None
            pm = parent[s["perm_in"]]            # global → permuted
            if kind == "hop":
                out = _ell_hop(s["prepared"], pm, W,
                               dtype)[s["out_idx"]]
                if filt is not None:
                    out = out & filt
                outs.append(out)
                results.append(out)
                continue
            # recurse: iterate in permuted space (no per-hop translation)
            filt_p = filt[s["perm_in"]] if filt is not None else None

            def hop(carry, _, _prep=s["prepared"], _filt_p=filt_p):
                frontier, seen = carry
                nxt = _ell_hop(_prep, frontier, W, dtype)
                fresh = nxt & ~seen
                if _filt_p is not None:
                    fresh = fresh & _filt_p
                seen = seen | fresh
                return (fresh, seen), (fresh if s["keep_hops"] else None)

            (_last, seen_p), hops_p = lax.scan(
                hop, (pm, pm), None, length=s["depth"])
            seen = seen_p[s["out_idx"]]
            outs.append(seen)
            if s["keep_hops"]:
                results.append((seen, hops_p[:, s["out_idx"]]))
            else:
                results.append(seen)
        return tuple(results)

    return run


def ell_recurse(g: EllGraph, mask0, depth: int, count_edges: bool = True):
    """One-shot convenience: device_put the blocks and run. For repeated
    runs hold make_ell_recurse + device arrays instead."""
    import numpy as np
    word_bits = 64 if np.asarray(mask0).dtype == np.uint64 else 32
    if word_bits == 64:
        assert jax.config.jax_enable_x64, \
            "uint64 lane words need x64 (jax.experimental.enable_x64)"
    dev = device_ell(g)
    fn = make_ell_recurse(dev, g.outdeg, g.n, mask0.shape[1],
                          count_edges, word_bits)
    return fn(jax.device_put(mask0), depth)
