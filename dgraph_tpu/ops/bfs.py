"""Batched traversal over lane-packed frontier bitmaps — the throughput path.

Reference parity: the reference serves concurrent queries with goroutines,
each walking posting lists independently (worker/task.go, one goroutine per
`ProcessTaskOverNetwork`; LDBC SNB IC mixes in BASELINE.json run many
queries at once). The TPU-native equivalent batches B concurrent traversals
into the *lanes* of a dense frontier bitmap:

    mask[n_nodes, B] int8      mask[v, q] = 1 iff node v is in query q's set

One hop for ALL queries is two wide array ops over the COO edge list:

    active  = mask[src]                  row-gather   [E, B]
    next    = zeros.at[dst].max(active)  row-scatter  [N, B]

The point is access *width*: TPU random gather/scatter costs are bounded by
access count, not bytes (measured ~8 ns/access on v5e regardless of row
width), so widening each access to a B-byte lane row amortises the
irregular-memory tax across B queries — the same shape the reference can't
reach because its per-query goroutines share nothing.

Per-query edges-traversed counts (the north-star metric) fall out of a
`deg · mask` matmul on the MXU. Counts are exact while a single hop
traverses < 2^24 edges per query (f32 mantissa); the int32 accumulator is
exact to 2^31 total.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ranks_to_bitmap", "bitmap_to_ranks", "bitmap_hop",
           "bitmap_recurse"]


def ranks_to_bitmap(rank_lists, n_nodes: int) -> jnp.ndarray:
    """Host helper: B rank lists → [n_nodes, B] int8 frontier bitmap."""
    import numpy as np
    out = np.zeros((n_nodes, len(rank_lists)), np.int8)
    for q, ranks in enumerate(rank_lists):
        out[np.asarray(ranks, np.int64), q] = 1
    return out


def bitmap_to_ranks(mask) -> list:
    """Host helper: [n_nodes, B] bitmap → list of B sorted rank arrays."""
    import numpy as np
    m = np.asarray(mask)
    return [np.nonzero(m[:, q])[0].astype(np.int32)
            for q in range(m.shape[1])]


@jax.jit
def bitmap_hop(src: jax.Array, dst: jax.Array, mask: jax.Array) -> jax.Array:
    """One hop of B concurrent traversals: next[v,q] = OR over edges u→v of
    mask[u,q]. `src`/`dst` are the COO edge list ([E] int32, any order)."""
    active = jnp.take(mask, src, axis=0, mode="clip")
    return jnp.zeros_like(mask).at[dst].max(active, mode="drop")


@functools.partial(jax.jit, static_argnames=("depth",))
def bitmap_recurse(src: jax.Array, dst: jax.Array, deg: jax.Array,
                   mask0: jax.Array, depth: int):
    """Depth-bounded loop=false @recurse for B queries at once, fully fused.

    `deg[n_nodes] int32` is the out-degree vector (for edge counting);
    `mask0[n_nodes, B] int8` holds each query's seed set. Returns
    `(last[n,B], seen[n,B], edges[B] int32)` where `seen` is each query's
    visited set (reference: expandRecurse's seen map per query) and
    `edges[q]` counts edges traversed from every expanded frontier — the
    north-star counter.
    """
    degf = deg.astype(jnp.float32)

    def hop(carry, _):
        frontier, seen, edges = carry
        # per-query frontier out-degree sum — one MXU matvec
        hop_edges = degf @ frontier.astype(jnp.float32)
        edges = edges + hop_edges.astype(jnp.int32)
        nxt = bitmap_hop(src, dst, frontier)
        fresh = jnp.where(seen > 0, jnp.int8(0), nxt)
        seen = jnp.maximum(seen, fresh)
        return (fresh, seen, edges), None

    B = mask0.shape[1]
    (last, seen, edges), _ = lax.scan(
        hop, (mask0, mask0, jnp.zeros((B,), jnp.int32)), None, length=depth)
    return last, seen, edges
