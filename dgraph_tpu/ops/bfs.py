"""Batched traversal over lane-packed frontier bitmaps — the throughput path.

Reference parity: the reference serves concurrent queries with goroutines,
each walking posting lists independently (worker/task.go, one goroutine per
`ProcessTaskOverNetwork`; LDBC SNB IC mixes in BASELINE.json run many
queries at once). The TPU-native equivalent batches B concurrent traversals
into the *lanes* of a dense frontier bitmap:

    mask[n_nodes, B] int8      mask[v, q] = 1 iff node v is in query q's set

One hop for ALL queries is two wide array ops over the COO edge list:

    active  = mask[src]                  row-gather   [E, B]
    next    = zeros.at[dst].max(active)  row-scatter  [N, B]

The point is access *width*: TPU random gather/scatter costs are bounded by
access count, not bytes (measured ~8 ns/access on v5e regardless of row
width), so widening each access to a B-byte lane row amortises the
irregular-memory tax across B queries — the same shape the reference can't
reach because its per-query goroutines share nothing.

Per-query edges-traversed counts (the north-star metric) fall out of a
`deg · mask` matmul on the MXU. Counts are exact while a single hop
traverses < 2^24 edges per query (f32 mantissa); the int32 accumulator is
exact to 2^31 total.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ranks_to_bitmap", "bitmap_to_ranks", "bitmap_hop",
           "bitmap_recurse", "EllGraph", "build_ell", "ell_recurse",
           "make_ell_tree", "pack_seed_masks", "unpack_masks"]


def ranks_to_bitmap(rank_lists, n_nodes: int) -> jnp.ndarray:
    """Host helper: B rank lists → [n_nodes, B] int8 frontier bitmap."""
    import numpy as np
    out = np.zeros((n_nodes, len(rank_lists)), np.int8)
    for q, ranks in enumerate(rank_lists):
        out[np.asarray(ranks, np.int64), q] = 1
    return out


def bitmap_to_ranks(mask) -> list:
    """Host helper: [n_nodes, B] bitmap → list of B sorted rank arrays."""
    import numpy as np
    m = np.asarray(mask)
    return [np.nonzero(m[:, q])[0].astype(np.int32)
            for q in range(m.shape[1])]


@jax.jit
def bitmap_hop(src: jax.Array, dst: jax.Array, mask: jax.Array) -> jax.Array:
    """One hop of B concurrent traversals: next[v,q] = OR over edges u→v of
    mask[u,q]. `src`/`dst` are the COO edge list ([E] int32, any order)."""
    active = jnp.take(mask, src, axis=0, mode="clip")
    return jnp.zeros_like(mask).at[dst].max(active, mode="drop")


@functools.partial(jax.jit, static_argnames=("depth",))
def bitmap_recurse(src: jax.Array, dst: jax.Array, deg: jax.Array,
                   mask0: jax.Array, depth: int):
    """Depth-bounded loop=false @recurse for B queries at once, fully fused.

    `deg[n_nodes] int32` is the out-degree vector (for edge counting);
    `mask0[n_nodes, B] int8` holds each query's seed set. Returns
    `(last[n,B], seen[n,B], edges[B] int32)` where `seen` is each query's
    visited set (reference: expandRecurse's seen map per query) and
    `edges[q]` counts edges traversed from every expanded frontier — the
    north-star counter.
    """
    degf = deg.astype(jnp.float32)

    def hop(carry, _):
        frontier, seen, edges = carry
        # per-query frontier out-degree sum — one MXU matvec
        hop_edges = degf @ frontier.astype(jnp.float32)
        edges = edges + hop_edges.astype(jnp.int32)
        nxt = bitmap_hop(src, dst, frontier)
        fresh = jnp.where(seen > 0, jnp.int8(0), nxt)
        seen = jnp.maximum(seen, fresh)
        return (fresh, seen, edges), None

    B = mask0.shape[1]
    (last, seen, edges), _ = lax.scan(
        hop, (mask0, mask0, jnp.zeros((B,), jnp.int32)), None, length=depth)
    return last, seen, edges


# ---------------------------------------------------------------------------
# ELL pull-hop: the access-amortised form of the batched traversal.
#
# The push kernel above pays one random row-gather AND one random
# row-scatter per edge. Measured on v5e, random row access costs ~10 ns
# REGARDLESS of row width (32 B or 256 B rows: 149 ms vs 181 ms for 16.5M
# accesses), so the winning shape is: (1) eliminate the scatter entirely by
# pulling over in-neighbor lists, and (2) amortise each access over as many
# concurrent queries as fit in the row (bit-packed lanes: W uint32 words =
# 32·W queries per access). One hop is then pure gathers + bitwise ORs —
# no scatter, no sort, fully static shapes.
#
# Layout: nodes are RENUMBERED by in-degree bucket (K = 1, 4, 16, ... —
# first power-of-4 ≥ indeg) so each bucket's output is a contiguous slice
# and the next-frontier mask is rebuilt by concatenation, not scatter.
# nbr[b] is [n_b, K_b] int32 of in-neighbors in the permuted space, padded
# with n (a sentinel all-zero mask row). Reference: this plays codec/'s
# role of making posting data compact AND the UidPack role of block
# iteration — but shaped for the MXU/VPU instead of varint decode.


@dataclass
class EllGraph:
    """In-neighbor ELL blocks over a degree-bucket permuted rank space."""

    n: int                                  # node count
    ells: list                              # per-bucket [n_b, K_b] int32
    outdeg: object                          # [n] f32, permuted space
    perm_order: object                      # new rank -> old rank
    new_of_old: object                      # old rank -> new rank
    ks: list = field(default_factory=list)  # bucket widths

    @property
    def nnz(self) -> int:
        return int(self.outdeg.sum())

    @property
    def padded_edges(self) -> int:
        return sum(int(e.size) for e in self.ells)


def build_ell(indptr, indices, bucket_base: int = 4) -> EllGraph:
    """Build pull-side ELL blocks from a CSR relation (host-side, once per
    snapshot). `bucket_base` trades padding (lower) against program count
    (higher): base 4 measured ~2.1x padding on powerlaw graphs."""
    import numpy as np

    n = indptr.shape[0] - 1
    deg_out = np.diff(indptr).astype(np.int64)
    src = np.repeat(np.arange(n, dtype=np.int32), deg_out)
    order = np.argsort(indices, kind="stable")
    csrc = src[order]                       # in-neighbors grouped by dst
    cdst = indices[order]
    cindptr = np.searchsorted(cdst, np.arange(n + 1)).astype(np.int64)
    indeg = np.diff(cindptr)

    max_indeg = max(int(indeg.max()), 1) if n else 1
    ks, k = [], 1
    # graftlint: allow(hot-loop-checkpoint): O(log max_indeg) ladder
    while k < max_indeg:
        ks.append(k)
        k *= bucket_base
    ks.append(max(k, 1))
    ks = sorted(set(ks))
    bucket_of = np.searchsorted(np.array(ks), indeg)
    perm_order = np.argsort(bucket_of, kind="stable")
    new_of_old = np.empty(n, np.int64)
    new_of_old[perm_order] = np.arange(n)
    counts = np.bincount(bucket_of, minlength=len(ks))
    offs = np.concatenate([[0], np.cumsum(counts)])

    ells = []
    for bi, K in enumerate(ks):
        nodes = perm_order[offs[bi]:offs[bi + 1]]
        nb = np.full((len(nodes), K), n, np.int32)   # n = sentinel row
        if len(nodes):
            deg = indeg[nodes]
            flat = np.concatenate(
                [np.arange(cindptr[v], cindptr[v] + deg[i])
                 for i, v in enumerate(nodes)]) if deg.sum() else \
                np.empty(0, np.int64)
            rowpos = np.repeat(np.arange(len(nodes)), deg)
            colpos = (np.arange(len(rowpos))
                      - np.repeat(np.cumsum(deg) - deg, deg))
            nb[rowpos, colpos] = new_of_old[csrc[flat]]
        ells.append(nb)
    return EllGraph(n=n, ells=ells,
                    outdeg=deg_out[perm_order].astype(np.float32),
                    perm_order=perm_order, new_of_old=new_of_old, ks=ks)


def pack_seed_masks(g: EllGraph, rank_lists) -> "jnp.ndarray":
    """B seed rank lists (OLD rank space) → [n+1, B/32] packed uint32 mask
    in the permuted space, sentinel zero row last. B must be a multiple of
    32."""
    import numpy as np
    B = len(rank_lists)
    assert B % 32 == 0, "lane count must pack into uint32 words"
    m = np.zeros((g.n + 1, B // 32), np.uint32)
    for q, ranks in enumerate(rank_lists):
        r = g.new_of_old[np.asarray(ranks, np.int64)]
        m[r, q // 32] |= np.uint32(1 << (q % 32))
    return m


def unpack_masks(g: EllGraph, mask) -> list:
    """[n+1, W] packed mask → list of B sorted OLD-rank arrays."""
    import numpy as np
    m = np.asarray(mask)[:g.n]
    out = []
    for q in range(m.shape[1] * 32):
        rows = np.nonzero((m[:, q // 32] >> np.uint32(q % 32)) & 1)[0]
        out.append(np.sort(g.perm_order[rows]).astype(np.int32))
    return out


# bytes a single bucket gather may NOMINALLY materialise before
# row-chunking. XLA usually fuses the gather into the OR-reduce without
# materialising, so this is NOT a real memory model — it exists solely to
# break up shapes XLA's fusion gives up on (observed: ~20G at B=8192),
# because the chunked form (lax.map) serialises and costs ~35% throughput
# wherever fusion would have worked
GATHER_BUDGET = 12 << 30


def _prepare_buckets(ells, n: int, W: int):
    """Pre-shape each ELL bucket for the hop at lane width W: buckets
    whose nominal gather intermediate fits GATHER_BUDGET stay flat;
    larger ones are padded + reshaped to [nch, ch, K] ONCE, eagerly (one
    device array — the jitted program must not carry both the original
    and a padded copy as constants). Under DGRAPH_TPU_PALLAS=1, every
    bucket is instead row-padded for the Pallas DMA-ring hop
    (ops/pallas_hop.py) — which streams rows through VMEM and has no
    gather intermediate to budget."""
    import os
    use_pallas = os.environ.get("DGRAPH_TPU_PALLAS", "") == "1"
    if use_pallas:
        # import only under the flag: the default XLA path must not
        # couple to the experimental pallas namespace
        from dgraph_tpu.ops.pallas_hop import BLOCK_ROWS
    prepared = []
    for e in ells:
        n_b, K = e.shape
        if use_pallas:
            if n_b == 0:
                # empty degree bucket: zero rows, zero work (the padded
                # sentinel block would DMA-loop for nothing every hop)
                prepared.append(("pallas", None, 0))
                continue
            padded = -(-n_b // BLOCK_ROWS) * BLOCK_ROWS
            if padded == n_b:
                e_p = jnp.asarray(e, jnp.int32)   # no copy when aligned
            else:
                pad = jnp.full((padded - n_b, K), n, jnp.int32)
                e_p = jnp.concatenate([jnp.asarray(e, jnp.int32), pad])
            prepared.append(("pallas", e_p, n_b))
            continue
        row_bytes = max(K * W * 4, 1)
        if n_b * row_bytes <= GATHER_BUDGET:
            prepared.append(("flat", jnp.asarray(e), n_b))
            continue
        ch = max(1, min(GATHER_BUDGET // row_bytes, n_b))
        nch = -(-n_b // ch)
        pad = jnp.full((nch * ch - n_b, K), n, jnp.int32)  # zero mask row
        e3 = jnp.concatenate([jnp.asarray(e, jnp.int32), pad]
                             ).reshape(nch, ch, K)
        prepared.append(("chunked", e3, n_b))
    return prepared


# Sticky fail-safe: the first bucket_hop_pallas that fails to trace or
# compile flips this and every pallas bucket (this one included) falls
# back to the XLA gather hop — an untested Mosaic compile must degrade
# a perf experiment, never burn the serving path (or a chip window).
_pallas_failed = False


def _pallas_bucket_part(e, n_b, frontier):
    """One pallas bucket's hop with XLA-gather fallback. The padded rows
    index frontier's all-zero sentinel row, so the gather form is exact
    on the same padded input; the fallback skips the chunked-budget
    shape (this is a failure path, not the tuned one)."""
    global _pallas_failed
    from dgraph_tpu.utils.metrics import METRICS
    if not _pallas_failed:
        try:
            from dgraph_tpu.ops.pallas_hop import bucket_hop_pallas
            return bucket_hop_pallas(e, frontier)[:n_b]
        except Exception:  # noqa: BLE001 — any trace/compile failure
            _pallas_failed = True
            METRICS.set_gauge("pallas_degraded", 1.0)
            from dgraph_tpu.utils import logging as xlog
            xlog.get("ops").warning(
                "pallas hop failed to trace/compile; falling back to "
                "the XLA gather hop for every bucket (perf experiment "
                "degraded, results unaffected)", exc_info=True)
    # counted per fallback BUCKET TRACE (this body runs at trace time,
    # once per compiled program, not per execution): the sticky
    # degradation stays visible in /debug/prometheus_metrics instead of
    # one log line scrolling away
    METRICS.inc("pallas_fallback_total")
    return lax.reduce(frontier[e], jnp.uint32(0),
                      lax.bitwise_or, (1,))[:n_b]


def _ell_hop(prepared, frontier, W):
    """next[v] = OR of frontier[u] over in-neighbors u — gathers only.
    Chunked buckets reduce row-slabs sequentially (lax.map) to bound the
    intermediate where XLA's gather+reduce fusion gives up (~20G);
    "pallas" buckets ride the explicit DMA-ring kernel instead of the
    XLA gather (ops/pallas_hop.py), falling back to the gather if the
    kernel fails to trace/compile (_pallas_bucket_part)."""
    parts = []
    for kind, e, n_b in prepared:
        if kind == "pallas":
            if n_b == 0:
                parts.append(jnp.zeros((0, W), jnp.uint32))
                continue
            parts.append(_pallas_bucket_part(e, n_b, frontier))
        elif kind == "flat":
            parts.append(lax.reduce(frontier[e], jnp.uint32(0),
                                    lax.bitwise_or, (1,)))
        else:
            out = lax.map(
                lambda c: lax.reduce(frontier[c], jnp.uint32(0),
                                     lax.bitwise_or, (1,)), e)
            parts.append(out.reshape(-1, W)[:n_b])
    parts.append(jnp.zeros((1, W), jnp.uint32))       # sentinel row
    return jnp.concatenate(parts, axis=0)


COUNT_BLK = 1 << 15   # edge-counter node-block rows (bounds unpack memory)


def make_ell_recurse(ells, outdeg, n: int, W: int, count_edges: bool = True):
    """Compile a depth-parameterised loop=false @recurse over an EllGraph
    already resident on device. Returns fn(mask0, depth) →
    (last[n+1,W], seen[n+1,W], edges[B] int32)."""
    nblk = -(-n // COUNT_BLK)
    n_pad = nblk * COUNT_BLK
    prepared = _prepare_buckets(ells, n, W)
    if count_edges:
        outdeg_pad = jnp.concatenate(
            [jnp.asarray(outdeg),
             jnp.zeros((n_pad - n,), jnp.float32)])

    def _count(frontier, edges):
        # per-query frontier out-degree mass: unpack the packed lanes and
        # matvec on the MXU (f32 exact to 2^24 per hop per query; int32
        # accumulator exact to 2^31). Blocked over node rows — a whole-
        # array unpack materialises n*W*32 floats and blows HBM at wide B.
        fpad = jnp.concatenate(
            [frontier[:n], jnp.zeros((n_pad - n, W), jnp.uint32)])

        def body(i, acc):
            sl = lax.dynamic_slice_in_dim(fpad, i * COUNT_BLK,
                                          COUNT_BLK, 0)
            od = lax.dynamic_slice_in_dim(outdeg_pad, i * COUNT_BLK,
                                          COUNT_BLK, 0)
            bits = ((sl[:, :, None] >> jnp.arange(32, dtype=jnp.uint32))
                    & 1).astype(jnp.float32).reshape(COUNT_BLK, W * 32)
            return acc + od @ bits

        hop_edges = lax.fori_loop(
            0, nblk, body, jnp.zeros((W * 32,), jnp.float32))
        return edges + hop_edges.astype(jnp.int32)

    @functools.partial(jax.jit, static_argnames=("depth", "keep_hops"))
    def recurse(mask0, depth: int, keep_hops: bool = False):
        def hop(carry, _):
            frontier, seen, edges = carry
            if count_edges:
                edges = _count(frontier, edges)
            nxt = _ell_hop(prepared, frontier, W)
            fresh = nxt & ~seen
            seen = seen | fresh
            return (fresh, seen, edges), (fresh if keep_hops else None)

        (last, seen, edges), hops = lax.scan(
            hop, (mask0, mask0, jnp.zeros((W * 32,), jnp.int32)), None,
            length=depth)
        if keep_hops:
            # hops[h] = the FRESH mask after hop h+1 (first-visit sets) —
            # what tree reconstruction needs (engine batch path)
            return last, seen, edges, hops
        return last, seen, edges

    return recurse


def make_ell_tree(stages, n: int, W: int):
    """Compile a level-TREE pipeline over lane-packed masks: the batched
    form of a whole nested query (engine/treebatch.py), one fused XLA
    program for B = 32·W concurrent queries.

    Reference parity: query/query.go ProcessGraph descends a SubGraph
    tree level by level, one task per child per goroutine; here every
    level of every lane is one stage of this program, and filters are
    bitmask ANDs instead of per-uid IntersectSorted calls.

    All masks live in the STORE's global rank space, shape [n+1, W]
    uint32 (row n = sentinel, always zero). Each stage's EllGraph has its
    own degree-bucket permutation, so a stage translates its parent mask
    into its own permuted space (one row gather), does the ELL pull-hop,
    and translates back (one row gather) — both translations stream
    sequentially and are noise next to the edge gather.

    `stages` is a list of dicts (static structure, device arrays):
      kind      "hop" | "recurse"
      prepared  _prepare_buckets output for the stage's EllGraph
      perm_in   [n+1] int32 device: permuted row r ← global perm_in[r]
      out_idx   [n+1] int32 device: global row v ← permuted out_idx[v]
      parent    ("seed", slot) | ("stage", idx earlier in the list)
      filt      filter-mask slot index | None  (global space, ANDed in)
      depth     recurse only: hop count (static)
      keep_hops recurse only: also return per-hop first-visit masks

    Returns fn(seeds: tuple, filts: tuple) → tuple with one entry per
    stage: hop → mask [n+1, W]; recurse → seen [n+1, W] (reachable set
    incl. seeds) or (seen, hops [depth, n+1, W]) when keep_hops.
    """

    @jax.jit
    def run(seeds, filts):
        outs = []
        results = []
        for s in stages:
            kind, par = s["kind"], s["parent"]
            parent = (seeds[par[1]] if par[0] == "seed"
                      else outs[par[1]])
            filt = filts[s["filt"]] if s["filt"] is not None else None
            pm = parent[s["perm_in"]]            # global → permuted
            if kind == "hop":
                out = _ell_hop(s["prepared"], pm, W)[s["out_idx"]]
                if filt is not None:
                    out = out & filt
                outs.append(out)
                results.append(out)
                continue
            # recurse: iterate in permuted space (no per-hop translation)
            filt_p = filt[s["perm_in"]] if filt is not None else None

            def hop(carry, _, _prep=s["prepared"], _filt_p=filt_p):
                frontier, seen = carry
                nxt = _ell_hop(_prep, frontier, W)
                fresh = nxt & ~seen
                if _filt_p is not None:
                    fresh = fresh & _filt_p
                seen = seen | fresh
                return (fresh, seen), (fresh if s["keep_hops"] else None)

            (_last, seen_p), hops_p = lax.scan(
                hop, (pm, pm), None, length=s["depth"])
            seen = seen_p[s["out_idx"]]
            outs.append(seen)
            if s["keep_hops"]:
                results.append((seen, hops_p[:, s["out_idx"]]))
            else:
                results.append(seen)
        return tuple(results)

    return run


def ell_recurse(g: EllGraph, mask0, depth: int, count_edges: bool = True):
    """One-shot convenience: device_put the blocks and run. For repeated
    runs hold make_ell_recurse + device arrays instead."""
    ells_d = [jax.device_put(e) for e in g.ells]
    outdeg_d = jax.device_put(g.outdeg)
    fn = make_ell_recurse(ells_d, outdeg_d, g.n, mask0.shape[1],
                          count_edges)
    return fn(jax.device_put(mask0), depth)
