"""Fused multi-hop @recurse as ONE compiled single-device program.

Reference parity: `query/recurse.go` (expandRecurse) — the north-star
workload. The reference's outer loop (re-seed SubGraph, re-run ProcessGraph
per depth) becomes a `lax.scan` over hops, so an entire depth-k traversal is
a single XLA program with zero host round-trips: each hop is gather →
sort-unique → seen-set subtraction, all fused.

TPU design note: the seen set is a dense int8 bitmap over rank space, not a
sorted list — membership is one vectorised gather instead of the
log2(n)-round binary search a sorted-set difference costs on TPU (measured
~50× slower). The sorted-list form (`uidalgebra.difference_sorted`) remains
for the small host-side paths.

The multi-device version (shard_map + collectives) lives in
`parallel/dhop.py::recurse_fused`; this is its single-chip core and the
kernel `bench.py` times on real TPU hardware.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from dgraph_tpu.ops.hop import gather_edges
from dgraph_tpu.ops.uidalgebra import (
    _member, compact_with_count, sentinel, sort_unique_count, valid_mask)


def masked_hop(indptr, indices, frontier, allowed, seen_mask,
               edge_cap: int, out_cap: int, use_allowed: bool):
    """One visit-once @recurse hop with the filter fused into the gather
    mask — the per-hop body of the whole-query fused program
    (engine/fused.py): the single-device sibling of `recurse_frontier`'s
    scan body that ALSO keeps the per-hop edge matrix (parents render)
    and the filter's allowed-set membership test, so a filtered
    `@recurse` block compiles into one program instead of per-hop
    expand → filter → subtract host passes.

    `frontier` is sorted sentinel-padded; `seen_mask` is the dense int8
    visited bitmap over rank space (ops/recurse.py design note).
    Returns `(nbrs[edge_cap], seg[edge_cap], n_kept, nxt[out_cap],
    n_unique, seen_mask, total)`: kept edges compacted to the front in
    CSR row order (the host loop's `nbrs[keep]` order), the deduped
    fresh frontier, the updated bitmap, and the raw gathered edge count
    (`total > edge_cap` ⇒ re-run bigger; `n_unique > out_cap` ⇒ same)."""
    n_nodes = indptr.shape[0] - 1
    nbrs, seg, _pos, valid, total = gather_edges(
        indptr, indices, frontier, edge_cap)
    keep = valid
    if use_allowed:
        keep = keep & _member(nbrs, allowed)
    visited = jnp.take(seen_mask, jnp.clip(nbrs, 0, n_nodes - 1),
                       mode="clip") > 0
    keep = keep & ~visited
    snt = sentinel(nbrs.dtype)
    m_nbrs = jnp.where(keep, nbrs, snt)
    m_seg = jnp.where(keep, seg, jnp.int32(2**31 - 1))
    # compact kept edges to the front preserving CSR row order (kept
    # slot keys are unique, so the argsort is deterministic)
    slot_key = jnp.where(keep, jnp.arange(edge_cap, dtype=jnp.int32),
                         jnp.int32(edge_cap))
    order = jnp.argsort(slot_key)
    n_kept = jnp.sum(keep.astype(jnp.int32))
    nxt, n_unique = sort_unique_count(m_nbrs, out_cap)
    # sentinel padding >= n_nodes: mode="drop" discards it
    seen_mask = seen_mask.at[nxt].set(jnp.int8(1), mode="drop")
    return (m_nbrs[order], m_seg[order], n_kept, nxt, n_unique,
            seen_mask, total)


@functools.partial(jax.jit,
                   static_argnames=("edge_cap", "out_cap", "seen_cap", "depth"))
def recurse_frontier(indptr: jax.Array, indices: jax.Array,
                     frontier: jax.Array, edge_cap: int, out_cap: int,
                     seen_cap: int, depth: int):
    """Depth-bounded loop-free @recurse over one CSR relation, fully fused.

    `frontier` must be sorted, sentinel-padded to exactly `out_cap` (it is
    the per-hop frontier buffer carried through the scan). Returns
    `(last_frontier[out_cap], seen[seen_cap], edges_traversed, needs[3])`
    with `needs = [max frontier slots, n visited, max edge slots]` — results
    are valid only if `needs <= [out_cap, seen_cap, edge_cap]` elementwise;
    otherwise re-run with the caps `needs` asks for (the same overflow
    contract as ops.hop.expand_frontier).
    """
    if frontier.shape[0] != out_cap:
        raise ValueError(
            f"frontier buffer {frontier.shape[0]} != out_cap {out_cap}")
    n_nodes = indptr.shape[0] - 1

    def mark(mask, uids):
        # sentinel padding >= n_nodes, so mode="drop" discards it
        return mask.at[uids].set(jnp.int8(1), mode="drop")

    def hop(carry, _):
        fr, seen_mask, edges, need_out, need_edge = carry
        nbrs, _seg, _pos, _valid, total = gather_edges(
            indptr, indices, fr, edge_cap)
        merged, mcnt = sort_unique_count(nbrs, out_cap)
        # loop=false: a node expands at most once — bitmap membership test
        visited = jnp.take(seen_mask, jnp.clip(merged, 0, n_nodes - 1),
                           mode="clip") > 0
        keep = valid_mask(merged) & ~visited
        fresh, _ = compact_with_count(merged, keep, out_cap)
        seen_mask = mark(seen_mask, fresh)
        return (fresh, seen_mask, edges + total,
                jnp.maximum(need_out, mcnt),
                jnp.maximum(need_edge, total)), None

    seen0 = mark(jnp.zeros((n_nodes,), jnp.int8), frontier)
    (last, seen_mask, edges, need_out, need_edge), _ = lax.scan(
        hop, (frontier, seen0, jnp.int32(0), jnp.int32(0), jnp.int32(0)),
        None, length=depth)

    # materialise the visited set as a sorted padded uid list — iota is
    # already ascending, so compaction alone suffices (no sort)
    iota = jnp.arange(n_nodes, dtype=frontier.dtype)
    seen, n_seen = compact_with_count(iota, seen_mask > 0, seen_cap)
    return last, seen, edges, jnp.stack([need_out, n_seen, need_edge])
