"""Fused multi-hop @recurse as ONE compiled single-device program.

Reference parity: `query/recurse.go` (expandRecurse) — the north-star
workload. The reference's outer Python-equivalent loop (re-seed SubGraph,
re-run ProcessGraph per depth) becomes a `lax.scan` over hops, so an entire
depth-k traversal is a single XLA program with zero host round-trips: each
hop is gather → sort-unique → seen-set difference, all fused.

The multi-device version (shard_map + collectives) lives in
`parallel/dhop.py::recurse_fused`; this is its single-chip core, and the
kernel `bench.py` times on real TPU hardware.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from dgraph_tpu.ops.hop import gather_edges
from dgraph_tpu.ops.uidalgebra import difference_sorted, sort_unique_count


@functools.partial(jax.jit,
                   static_argnames=("edge_cap", "out_cap", "seen_cap", "depth"))
def recurse_frontier(indptr: jax.Array, indices: jax.Array,
                     frontier: jax.Array, edge_cap: int, out_cap: int,
                     seen_cap: int, depth: int):
    """Depth-bounded loop-free @recurse over one CSR relation, fully fused.

    `frontier` must be sorted, sentinel-padded to exactly `out_cap` (it is
    the per-hop frontier buffer carried through the scan). Returns
    `(last_frontier[out_cap], seen[seen_cap], edges_traversed, needs[3])`
    with `needs = [max frontier slots, max seen slots, max edge slots]` any
    hop required. Results are valid only if `needs <= [out_cap, seen_cap,
    edge_cap]` elementwise; otherwise re-run with the caps `needs` asks for
    (the same overflow contract as ops.hop.expand_frontier).
    """
    if frontier.shape[0] != out_cap:
        raise ValueError(
            f"frontier buffer {frontier.shape[0]} != out_cap {out_cap}")

    def hop(carry, _):
        fr, seen, edges, need_out, need_seen, need_edge = carry
        nbrs, _seg, _pos, _valid, total = gather_edges(
            indptr, indices, fr, edge_cap)
        merged, mcnt = sort_unique_count(nbrs, out_cap)
        # loop=false semantics: a node expands at most once (first visit).
        fresh = difference_sorted(merged, seen)
        seen, scnt = sort_unique_count(
            jnp.concatenate([seen, fresh]), seen_cap)
        return (fresh, seen, edges + total,
                jnp.maximum(need_out, mcnt),
                jnp.maximum(need_seen, scnt),
                jnp.maximum(need_edge, total)), None

    seen0, scnt0 = sort_unique_count(frontier, seen_cap)
    (last, seen, edges, need_out, need_seen, need_edge), _ = lax.scan(
        hop,
        (frontier, seen0, jnp.int32(0), jnp.int32(0), scnt0, jnp.int32(0)),
        None, length=depth)
    return last, seen, edges, jnp.stack([need_out, need_seen, need_edge])
