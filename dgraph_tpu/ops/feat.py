"""Segment-combine kernels: per-hop neighbour-feature aggregation.

FeatGraph's thesis (PAPERS: "FeatGraph") is that the gather/segment
machinery behind a hop generalizes when every node carries a dense
feature vector — the hop's `(neighbors, seg)` edge slots become the
index pairs of a sparse-dense row aggregation, the regime where dense
hardware wins widest ("Fast Training of Sparse GNNs on Dense
Hardware"). This module is that kernel family: given the flat edge
slots of one traversal level and a sorted embedding stack (a
`store/vec.py` VecTablet), combine each frontier position's in-edge
feature rows with sum / mean / max.

Contract (the bit-identity discipline every route is pinned against):

* An edge *participates* when its neighbour has a row in the stack;
  edges are aggregated per-EDGE (a neighbour reached twice counts
  twice — the kept-edge lists, not the unique node sets, define the
  combine).
* `mean` is the exact f32 sum divided by the f32 participant count —
  one IEEE division, identical on every route for exactly
  representable inputs (small-integer-valued fixtures).
* Segments with zero participating edges produce the zero vector; the
  caller distinguishes "no kept edges at all" via the structural edge
  count (`ecnt`) and omits those segments entirely.

Shapes are static (`n_seg`, `edge_cap` compile-time; `agg` selects the
program) with a validity mask carrying the dynamic edge count — the
same no-retrace discipline as ops/hop.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

AGGS = ("sum", "mean", "max")


def segment_combine(subj, vecs, nbrs, seg, valid, n_seg: int, agg: str,
                    mask_empty: bool = True):
    """Pure traceable core: combine feature rows of `nbrs[j]` into
    segment `seg[j]` for every valid edge slot.

    `subj` [rows] sorted unique int32 ranks, `vecs` [rows, d] f32 —
    a VecTablet's arrays (rows ≥ 1; the caller owns the empty-tablet
    case). Returns `(out[n_seg, d] f32, cnt[n_seg] i32, ecnt[n_seg]
    i32)`: the aggregate, the participating-edge count, and the
    structural kept-edge count per segment.

    `mask_empty=False` keeps the raw partials for cross-shard merges:
    `max` returns -inf rows and `mean` returns the undivided sum, so a
    pmax/psum over shards followed by one global mask/division stays
    bit-identical to the single-device program.
    """
    rows = subj.shape[0]
    idx = jnp.clip(jnp.searchsorted(subj, nbrs), 0, rows - 1)
    has = valid & (jnp.take(subj, idx) == nbrs)
    got = jnp.take(vecs, idx, axis=0)                       # [e, d]
    cnt = jnp.zeros((n_seg,), jnp.int32).at[seg].add(
        has.astype(jnp.int32), mode="drop")
    ecnt = jnp.zeros((n_seg,), jnp.int32).at[seg].add(
        valid.astype(jnp.int32), mode="drop")
    if agg == "max":
        neg = jnp.float32(-jnp.inf)
        out = jnp.full((n_seg, got.shape[1]), neg, jnp.float32).at[
            seg].max(jnp.where(has[:, None], got, neg), mode="drop")
        if mask_empty:
            out = jnp.where((cnt > 0)[:, None], out, jnp.float32(0))
    else:
        out = jnp.zeros((n_seg, got.shape[1]), jnp.float32).at[
            seg].add(jnp.where(has[:, None], got, jnp.float32(0)),
                     mode="drop")
        if agg == "mean" and mask_empty:
            out = jnp.where(
                (cnt > 0)[:, None],
                out / jnp.maximum(cnt, 1)[:, None].astype(jnp.float32),
                jnp.float32(0))
    return out, cnt, ecnt


@functools.partial(jax.jit, static_argnames=("n_seg", "agg"))
def combine_edges(subj, vecs, nbrs, seg, n_edges, n_seg: int, agg: str):
    """Jitted single-level entry: `nbrs`/`seg` are padded to a static
    edge bucket, `n_edges` (traced scalar) masks the live prefix."""
    valid = jnp.arange(nbrs.shape[0], dtype=jnp.int32) < n_edges
    return segment_combine(subj, vecs, nbrs, seg, valid, n_seg, agg)


def combine_key(rows: int, d: int, edge_cap: int, n_seg: int,
                agg: str) -> tuple:
    """The static configuration that forces a distinct XLA program for
    a segment-combine launch (the ops/hop.py `launch_key` discipline)."""
    return ("feat.agg", rows, d, edge_cap, n_seg, agg)
