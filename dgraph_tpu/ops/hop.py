"""The hop kernel: one query level as a single jitted CSR gather program.

Reference parity: the body of `query.SubGraph.ProcessGraph` →
`worker.processTask` → `posting.List.Uids` per-uid Go loops (query/query.go,
worker/task.go, posting/list.go). There, each frontier uid walks its posting
list pointer-by-pointer in a goroutine; here the WHOLE frontier expands in
one edge-parallel program:

    frontier ranks → degree gather → exclusive cumsum → edge-slot
    searchsorted → neighbour gather → (sort+unique) next frontier

Shapes are static (`edge_cap`, `out_cap` are compile-time), with validity
masks carrying the dynamic sizes — the discipline that keeps XLA from
retracing per query.

A "posting store" at this layer is just a CSR pair per (predicate,
direction): `indptr[int32, n_nodes+1]`, `indices[int32, nnz]` in rank space
(see store/). Values/facets ride parallel columnar arrays indexed by the
same edge positions.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from dgraph_tpu.ops.uidalgebra import sentinel, sort_unique_count, valid_mask


def launch_key(indptr, frontier, edge_cap: int,
               out_cap: int | None = None) -> tuple:
    """The static configuration that forces a distinct XLA program for a
    hop launch: CSR height (per predicate/direction), frontier bucket,
    and the edge/out caps. Compile-cache accounting (utils/jitcache)
    keys on exactly this tuple — anything else re-uses a cached
    executable."""
    return (int(indptr.shape[0]), int(frontier.shape[0]),
            int(edge_cap), out_cap)


@jax.jit
def frontier_degrees(indptr: jax.Array, frontier: jax.Array) -> jax.Array:
    """Out-degree of each frontier rank (0 for padding). Reference: List.ApproxLen/count index."""
    valid = valid_mask(frontier)
    f = jnp.where(valid, frontier, 0)
    deg = jnp.take(indptr, f + 1, mode="clip") - jnp.take(indptr, f, mode="clip")
    return jnp.where(valid, deg, 0).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("edge_cap",))
def gather_edges(indptr: jax.Array, indices: jax.Array, frontier: jax.Array,
                 edge_cap: int):
    """Expand every frontier node's posting list into flat edge slots.

    Returns (neighbors[edge_cap], seg[edge_cap], edge_pos[edge_cap],
    valid[edge_cap], total):
      - `seg[j]` is the frontier position that produced edge j — the
        UidMatrix row structure the reference keeps for nested JSON
        reconstruction (pb.Result.UidMatrix).
      - `edge_pos[j]` is the absolute position in `indices` — used to
        gather per-edge facet columns.
      - `total` is the true edge count; slots ≥ total are masked. If
        total > edge_cap the caller must re-run with a bigger bucket
        (the host-side bucketing loop owns that policy).
    """
    deg = frontier_degrees(indptr, frontier)
    offsets = jnp.cumsum(deg) - deg  # exclusive cumsum
    total = jnp.sum(deg)

    j = jnp.arange(edge_cap, dtype=jnp.int32)
    # Which frontier slot does edge j belong to? Scatter each non-empty
    # row's index at its start offset, then cummax-propagate. (TPU note:
    # searchsorted here lowers to ~log2(f_cap) serial gather rounds —
    # measured 50× slower than this scatter+scan form.)
    nonempty = deg > 0
    starts = jnp.where(nonempty, offsets, edge_cap)  # empty rows: dropped
    row_idx = jnp.arange(frontier.shape[0], dtype=jnp.int32)
    seg_marks = jnp.zeros((edge_cap,), jnp.int32).at[starts].max(
        row_idx, mode="drop")
    seg = lax.cummax(seg_marks)
    # Edge j's absolute position in `indices`: its row's indptr start plus
    # the within-row offset — one fused gather of (start - offset) per row.
    src_rank = jnp.where(valid_mask(frontier), frontier, 0)
    base = jnp.take(indptr, src_rank, mode="clip") - offsets  # [f_cap]
    edge_pos = base[seg] + j
    neighbors = jnp.take(indices, edge_pos, mode="clip")
    valid = j < total
    snt = sentinel(indices.dtype)
    neighbors = jnp.where(valid, neighbors, snt)
    return neighbors, seg, edge_pos, valid, total


@functools.partial(jax.jit, static_argnames=("edge_cap", "out_cap"))
def expand_frontier(indptr: jax.Array, indices: jax.Array, frontier: jax.Array,
                    edge_cap: int, out_cap: int):
    """One full hop: gather all edges, dedupe into the next sorted frontier.

    Reference: one level of ProcessGraph followed by the merge of child uid
    lists (algo.MergeSorted) that seeds the next level / recurse iteration.

    Overflow contract: `total > edge_cap` means edges were dropped;
    `nxt_count > out_cap` means the deduped frontier was truncated. Either
    way the host re-runs at the next bucket size — results with either
    condition true must not be used.
    """
    neighbors, seg, edge_pos, valid, total = gather_edges(
        indptr, indices, frontier, edge_cap)
    nxt, nxt_count = sort_unique_count(neighbors, out_cap)
    return nxt, nxt_count, neighbors, seg, edge_pos, valid, total
