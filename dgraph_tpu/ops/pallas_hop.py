"""Pallas TPU kernel: blocked ELL pull-hop with an explicit DMA prefetch ring.

Reference parity: this is the hot loop of every traversal — the role
`posting.List.Uids` + `codec` block decoding play per-uid in the
reference (SURVEY §3.1 🔥 marks), batched over lane-packed queries.

Why a hand-written kernel (BASELINE.md headroom note): the XLA form of
the hop (`ops/bfs.py _ell_hop`) is a gather + OR-reduce whose measured
throughput is ~12% of HBM peak — the random 512-byte row reads are
LATENCY-bound, not bandwidth-bound. XLA's gather bounds its outstanding
reads; this kernel controls the pipeline explicitly: an N_BUF-deep ring
of async row DMAs (HBM → VMEM) stays in flight while the VPU ORs the
rows that already landed, so row latency amortizes across the ring
depth instead of serializing.

Structure per grid step (one block of output rows):
  nbr block  [BR, K] int32   streamed to VMEM by the pallas pipeline
  frontier   [n+1, W] uint32 stays in HBM; rows DMA'd on demand
  out block  [BR, W] uint32  accumulated in VMEM, written back once
The flat edge loop issues the DMA for edge t+N_BUF before waiting on
edge t — the "prefetch pipelining" BASELINE.md names as the remaining
headroom. K is static per bucket (EllGraph's degree buckets), so each
bucket compiles its own specialization.

The kernel is correctness-tested on CPU via the pallas interpreter;
its perf claim is measured on hardware by `bench.py` under
DGRAPH_TPU_PALLAS=1 (see BASELINE.md).

MOSAIC CAVEAT (why the flag stays off by default): the DMA addresses
are data-dependent scalar reads from the VMEM nbr block; the canonical
TPU pattern routes such indices through SMEM scalar prefetch. The first
real-TPU compile must be smoke-tested before any hardware A/B (the
chip tunnel was wedged for the whole round this kernel landed in —
BASELINE.md tracks the pending on-silicon validation).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["bucket_hop_pallas", "pallas_enabled"]

BLOCK_ROWS = 256     # output rows per grid step
N_BUF = 16           # DMA ring depth (rows in flight)


def pallas_enabled() -> bool:
    """Opt-in flag: the Pallas hop replaces the XLA gather hop when
    DGRAPH_TPU_PALLAS=1 (kept opt-in until the on-silicon A/B in
    BASELINE.md says it wins by default)."""
    import os
    return os.environ.get("DGRAPH_TPU_PALLAS", "") == "1"


def _interpret() -> bool:
    # CPU/virtual-device runs (tests, dryruns) use the interpreter;
    # Mosaic compiles only on real TPU backends
    return jax.default_backend() != "tpu"


def _make_kernel(K: int, W: int, n_buf: int):
    # runs once per pallas_call CONSTRUCTION (i.e. per trace of
    # bucket_hop_pallas): counts Mosaic kernel builds per bucket width —
    # the observable that separates "compiling" from "wedged" when a
    # chip window goes quiet
    from dgraph_tpu.utils.metrics import METRICS
    METRICS.inc("pallas_kernel_builds_total", k=str(K), w=str(W))

    def kernel(nbr_ref, frontier_ref, out_ref, rows, sems):
        br = nbr_ref.shape[0]
        total = br * K

        def dma(t, slot):
            idx = nbr_ref[t // K, t % K]
            return pltpu.make_async_copy(
                frontier_ref.at[pl.ds(idx, 1), :],
                rows.at[slot], sems.at[slot])

        out_ref[:] = jnp.zeros_like(out_ref)
        # warm the ring (total = BR*K is static, python-level guard)
        for s in range(min(n_buf, total)):
            dma(s, s).start()

        def body(t, _):
            slot = t % n_buf
            dma(t, slot).wait()
            i = t // K
            out_ref[i, :] = out_ref[i, :] | rows[slot, 0, :]

            @pl.when(t + n_buf < total)
            def _():
                # reuse the slot just freed: the ring stays n_buf deep
                dma(t + n_buf, slot).start()
            return 0

        lax.fori_loop(0, total, body, 0)

    return kernel


@functools.partial(jax.jit, static_argnames=("block_rows", "n_buf"))
def bucket_hop_pallas(nbr: jax.Array, frontier: jax.Array,
                      block_rows: int = BLOCK_ROWS,
                      n_buf: int = N_BUF) -> jax.Array:
    """One ELL bucket's pull-hop: out[i] = OR_k frontier[nbr[i, k]].

    `nbr` is [n_b, K] int32 (rows padded with the sentinel row index —
    frontier's last, all-zero row); n_b must be a multiple of
    `block_rows` (ops/bfs.py pads buckets at prepare time). `frontier`
    is [n+1, W] uint32 and never leaves HBM — only the referenced rows
    move, through the DMA ring."""
    n_b, K = nbr.shape
    W = frontier.shape[1]
    assert n_b % block_rows == 0, (n_b, block_rows)
    return pl.pallas_call(
        _make_kernel(K, W, n_buf),
        out_shape=jax.ShapeDtypeStruct((n_b, W), jnp.uint32),
        grid=(n_b // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, K), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),        # frontier: HBM
        ],
        out_specs=pl.BlockSpec((block_rows, W), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((n_buf, 1, W), jnp.uint32),    # landed rows
            pltpu.SemaphoreType.DMA((n_buf,)),
        ],
        interpret=_interpret(),
    )(nbr, frontier)
