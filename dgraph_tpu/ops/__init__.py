"""TPU-native sorted-UID algebra and traversal kernels.

This package replaces the reference's hot inner loops (reference:
`algo/uidlist.go` IntersectSorted/MergeSorted/Difference/ApplyFilter/IndexOf,
`codec/codec.go` block decode) with jit-compiled, statically-shaped JAX
programs. UID sets are sorted integer arrays padded with a sentinel so every
op has a static output shape and XLA can fuse whole per-hop pipelines.
"""

from dgraph_tpu.ops.uidalgebra import (
    SENTINEL32,
    sentinel,
    valid_mask,
    count_valid,
    pad_to,
    compact,
    compact_with_count,
    sort_unique,
    sort_unique_count,
    intersect_sorted,
    merge_sorted,
    difference_sorted,
    index_of,
    contains,
    take_page,
)
from dgraph_tpu.ops.hop import gather_edges, frontier_degrees, expand_frontier

__all__ = [
    "SENTINEL32",
    "sentinel",
    "valid_mask",
    "count_valid",
    "pad_to",
    "compact",
    "compact_with_count",
    "sort_unique",
    "sort_unique_count",
    "intersect_sorted",
    "merge_sorted",
    "difference_sorted",
    "index_of",
    "contains",
    "take_page",
    "gather_edges",
    "frontier_degrees",
    "expand_frontier",
]
