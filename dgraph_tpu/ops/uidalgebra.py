"""Sorted-UID set algebra as statically-shaped JAX programs.

Reference parity: `algo/uidlist.go` (IntersectSorted, MergeSorted,
Difference, ApplyFilter, IndexOf) and the compact-list role of
`codec/codec.go`. The reference chooses between linear scan, binary search
and galloping per size ratio; on TPU one vectorised `searchsorted`
membership test is the right shape for every ratio — the "algorithm
selection" problem disappears into XLA.

Representation
--------------
A *uid set* is a 1-D integer array, sorted ascending, padded at the tail
with ``sentinel(dtype)`` (the dtype's max value). Real uids must be
strictly smaller than the sentinel. The padded representation gives every
op a static output shape — the compile-once contract jit needs — while
`count_valid` recovers the logical length in O(log n).

All ops are pure jnp (CPU/TPU agnostic) and safe to call under `jax.jit`
with the size arguments static.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

SENTINEL32 = np.iinfo(np.int32).max


def sentinel(dtype) -> int:
    """Padding value for a uid dtype: the dtype's maximum."""
    return int(jnp.iinfo(dtype).max)


def valid_mask(a: jax.Array) -> jax.Array:
    """Boolean mask of the non-padding elements."""
    return a != sentinel(a.dtype)


def count_valid(a: jax.Array) -> jax.Array:
    """Logical length of a padded sorted uid set (scalar int32)."""
    return jnp.searchsorted(a, jnp.asarray(sentinel(a.dtype), a.dtype)).astype(jnp.int32)


def pad_to(a, size: int, dtype=jnp.int32) -> jax.Array:
    """Pad (or validate) a host/device array to `size` with the sentinel."""
    a = jnp.asarray(a, dtype)
    n = a.shape[0]
    if n > size:
        raise ValueError(f"uid set of length {n} exceeds capacity {size}")
    return jnp.concatenate([a, jnp.full((size - n,), sentinel(dtype), dtype)])


def compact_with_count(values: jax.Array, keep: jax.Array, size: int):
    """Stably move `values[keep]` to the front of a sentinel-padded [size] array.

    The workhorse under intersect/difference/unique: a cumsum-position
    scatter (drop-out-of-bounds), which XLA lowers to a single fused
    scan+scatter. Preserves order, so sorted in → sorted out.

    Returns `(out, kept)` where `kept` is the TRUE number of kept elements.
    If `kept > size` the output was truncated (the tail beyond `size` is
    dropped) — callers that can overflow must check `kept` and re-run with
    a bigger bucket, mirroring how `gather_edges` signals via `total`.
    """
    snt = sentinel(values.dtype)
    kept = jnp.sum(keep.astype(jnp.int32))
    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    pos = jnp.where(keep, pos, size)  # out-of-bounds → dropped by scatter
    out = jnp.full((size,), snt, values.dtype)
    return out.at[pos].set(values, mode="drop"), kept


def compact(values: jax.Array, keep: jax.Array, size: int) -> jax.Array:
    """`compact_with_count` without the count — for callers whose `size`
    provably cannot overflow (e.g. intersect with size=len(a))."""
    return compact_with_count(values, keep, size)[0]


def _member(a: jax.Array, b: jax.Array) -> jax.Array:
    """For each element of `a`, whether it occurs in sorted padded `b`."""
    idx = jnp.searchsorted(b, a)
    idx = jnp.minimum(idx, b.shape[0] - 1)
    return (b[idx] == a) & valid_mask(a)


@functools.partial(jax.jit, static_argnames=("size",))
def intersect_sorted(a: jax.Array, b: jax.Array, size: int | None = None) -> jax.Array:
    """a ∩ b for sorted padded uid sets. Reference: algo.IntersectSorted."""
    if size is None:
        size = a.shape[0]
    return compact(a, _member(a, b), size)


@functools.partial(jax.jit, static_argnames=("size",))
def difference_sorted(a: jax.Array, b: jax.Array, size: int | None = None) -> jax.Array:
    """a \\ b for sorted padded uid sets. Reference: algo.Difference."""
    if size is None:
        size = a.shape[0]
    return compact(a, valid_mask(a) & ~_member(a, b), size)


@functools.partial(jax.jit, static_argnames=("size",))
def sort_unique_count(x: jax.Array, size: int):
    """Sort an arbitrary padded array, drop duplicates (and padding).

    The dedupe step of frontier construction: reference merges per-uid
    result lists via a k-way heap (`algo.MergeSorted`); on TPU a single
    bitonic sort + neighbour-compare + compaction is one fused program.

    Returns `(out[size], n_unique)`; `n_unique > size` means the output
    was truncated and the caller must re-run with a larger bucket.
    """
    s = jnp.sort(x)
    keep = valid_mask(s) & jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), s[1:] != s[:-1]]
    )
    return compact_with_count(s, keep, size)


def sort_unique(x: jax.Array, size: int) -> jax.Array:
    """`sort_unique_count` without the count — only safe when
    `size >= x.shape[0]` (cannot truncate)."""
    return sort_unique_count(x, size)[0]


@functools.partial(jax.jit, static_argnames=("size",))
def merge_sorted(a: jax.Array, b: jax.Array, size: int | None = None) -> jax.Array:
    """Deduplicating union of two sorted padded uid sets. Reference: algo.MergeSorted."""
    if size is None:
        size = a.shape[0] + b.shape[0]
    return sort_unique(jnp.concatenate([a, b]), size)


@jax.jit
def index_of(a: jax.Array, v) -> jax.Array:
    """Position of uid `v` in sorted padded `a`, or -1. Reference: algo.IndexOf."""
    v = jnp.asarray(v, a.dtype)
    idx = jnp.searchsorted(a, v)
    idx_c = jnp.minimum(idx, a.shape[0] - 1)
    return jnp.where(a[idx_c] == v, idx_c.astype(jnp.int32), jnp.int32(-1))


@jax.jit
def contains(a: jax.Array, v) -> jax.Array:
    """Whether sorted padded `a` contains uid `v` (scalar bool)."""
    return index_of(a, v) >= 0


@functools.partial(jax.jit, static_argnames=("size",))
def take_page(a: jax.Array, offset, first, size: int) -> jax.Array:
    """Pagination window over a sorted padded uid set.

    Reference: `first:`/`offset:` args applied to posting lists
    (query/query.go pagination). Negative `first` means "last |first|"
    as in the reference. `offset`/`first` are traced scalars so one
    compiled program serves every page.
    """
    n = count_valid(a)
    offset = jnp.asarray(offset, jnp.int32)
    first = jnp.asarray(first, jnp.int32)
    start = jnp.where(first < 0, jnp.maximum(n + first - offset, 0), offset)
    cnt = jnp.where(first < 0, jnp.minimum(-first, n - start),
                    jnp.where(first == 0, n - start, jnp.minimum(first, n - start)))
    cnt = jnp.maximum(cnt, 0)
    i = jnp.arange(a.shape[0], dtype=jnp.int32)
    src = jnp.minimum(i + start, a.shape[0] - 1)
    vals = a[src]
    return jnp.where(i < cnt, vals, sentinel(a.dtype))
