"""graftlint rules R1–R6: the invariants PRs 1–5 established, as code.

Each rule is deliberately a HEURISTIC with a waiver escape hatch, not a
proof system: the goal is that breaking an invariant during a refactor
requires writing a visible, reasoned waiver instead of passing silently.

R1 hot-loop-checkpoint   while-loops in engine/, ops/, cluster/ call
                         `checkpoint()` once per iteration (PR-4).
R2 direct-io             no outbound socket/gRPC/HTTP constructors
                         outside server/task.py's Client (PR-5).
R3 wall-clock            no `time.time()` — deadline/backoff arithmetic
                         is monotonic-only (PR-4); wall clock needs a
                         reasoned waiver (external timestamps only).
R4 retry-deadline        a retry loop (sleep + broad except) must
                         exclude DEADLINE_EXCEEDED / DeadlineExceeded /
                         Cancelled from re-attempts (PR-5).
R5 metric-docs           metric names are string literals, label sets
                         are explicit kwargs (no **splat), and every
                         name has a README observability-table row
                         (subsumes the PR-4 doc-lint).
R6 jit-purity            no `.item()`/`.tolist()`/numpy host ops or
                         Python branches on tracer params inside
                         functions handed to `jax.jit`.
R7 shard-map-compat      `shard_map` resolves ONLY through
                         utils/jaxcompat.py — direct `jax.shard_map` /
                         `jax.experimental.shard_map` references
                         elsewhere re-pin the mesh layer to one jax
                         version (the exact regression that parked the
                         whole parallel/ layer in the failure set).
R8 atomic-write          durable files under store/ (and
                         server/backup.py) land via tmp + fsync +
                         os.replace — a bare `open(..., "w"/"wb")`
                         there can tear under a kill where a reader
                         expects a whole file (ISSUE-11).

R9–R12 (lock discipline / data races) live in `guards.py` — the
Eraser-style static half of the race sanitizer (ISSUE 12).

R13 fused-host-callback  a jitted function in the fused-program layer
                         (engine/fused.py, ops/) may not call
                         costprofile/tracing/metrics/jit-accounting
                         host helpers inside the traced region — they
                         would run at TRACE time only (silent no-op on
                         cached executions) or force a host callback
                         into the one-launch program (ISSUE 15;
                         extends the R6 jit-purity facts to the fused
                         program inventory).
R14 cache-registration   byte-holding caches join the process memory
                         governor (ISSUE 16): every `Memo(...)` call
                         states its `governed=` decision explicitly,
                         and a file that grows a dict-typed `*_cache`
                         attribute must register with
                         `memgov.GOVERNOR.register` somewhere (or
                         waive with the reason its bytes are bounded)
                         — an unregistered cache is invisible to the
                         OOM evict-retry path and to /debug/memory.
R15 slo-spec             SLO names stay inside the utils/slo.SLO_SPECS
                         inventory (ISSUE 17): a literal `slo=` label
                         on a metric, a literal SLO_SPECS /
                         DEFAULT_TARGETS subscript, or a literal
                         `_evaluator("...")` registration naming an
                         objective the inventory doesn't carry would
                         split the burn-rate vocabulary — dashboards,
                         /debug/slo, and the watchdog conviction feed
                         would disagree on what objectives exist.
"""

from __future__ import annotations

import ast

from dgraph_tpu.analysis import FileContext, Finding, Rule

__all__ = ["default_rules", "HotLoopCheckpoint", "DirectIO", "WallClock",
           "RetryDeadline", "MetricDocs", "JitPurity", "ShardMapCompat",
           "FusedHostCallback", "AtomicWrite", "CacheRegistration",
           "SloSpec"]


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name for a call target: `a.b.c` or `name`;
    "" when the target is dynamic (subscript, call result, ...)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _walk_no_defs(node: ast.AST):
    """Walk a subtree without descending into nested function/class
    definitions (their bodies run in another context)."""
    todo = list(ast.iter_child_nodes(node))
    while todo:
        n = todo.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            todo.extend(ast.iter_child_nodes(n))


# ---------------------------------------------------------------------------
class HotLoopCheckpoint(Rule):
    name = "hot-loop-checkpoint"
    doc = ("unbounded-iteration (`while`) loops on the serving path "
           "must call `deadline.checkpoint()` once per iteration so a "
           "pathological query cancels within one loop body of its "
           "budget (the PR-4 contract)")

    SCOPES = ("dgraph_tpu/engine/", "dgraph_tpu/ops/",
              "dgraph_tpu/cluster/")

    def applies(self, rel: str) -> bool:
        return rel.startswith(self.SCOPES)

    def check_file(self, ctx: FileContext) -> list[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.While):
                continue
            has_cp = any(
                isinstance(n, ast.Call)
                and _dotted(n.func).rsplit(".", 1)[-1]
                in ("checkpoint", "check")
                for n in ast.walk(node))
            if not has_cp:
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    "while-loop without a deadline checkpoint — call "
                    "deadline.checkpoint(stage) once per iteration, or "
                    "waive with the bound that makes it safe"))
        return out


# ---------------------------------------------------------------------------
class DirectIO(Rule):
    name = "direct-io"
    doc = ("outbound network constructors are allowed only inside "
           "server/task.py's Client — everything else must ride "
           "`Client._call` so breakers/retries/budget forwarding "
           "apply (the PR-5 contract)")

    BANNED = frozenset({
        "grpc.insecure_channel", "grpc.secure_channel",
        "socket.socket", "socket.create_connection",
        "urllib.request.urlopen", "http.client.HTTPConnection",
        "http.client.HTTPSConnection", "requests.get", "requests.post",
        "requests.put", "requests.delete", "requests.request",
        "requests.Session",
    })

    def applies(self, rel: str) -> bool:
        return (rel.startswith("dgraph_tpu/")
                and rel != "dgraph_tpu/server/task.py")

    def check_file(self, ctx: FileContext) -> list[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d in self.BANNED:
                    out.append(Finding(
                        self.name, ctx.rel, node.lineno,
                        f"direct network call {d}() outside "
                        f"server/task.py Client._call — outbound RPCs "
                        f"must ride the resilience wrapper"))
        return out


# ---------------------------------------------------------------------------
class WallClock(Rule):
    name = "wall-clock"
    doc = ("no `time.time()` in the package — deadline/backoff "
           "arithmetic uses monotonic clocks (utils/deadline.py "
           "helpers); wall clock is only for timestamps that leave "
           "the process, and says so in a waiver")

    def check_file(self, ctx: FileContext) -> list[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and _dotted(node.func) in ("time.time",
                                               "_time.time")):
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    "wall-clock time.time() — deadline/backoff "
                    "arithmetic must use monotonic clocks "
                    "(utils/deadline.monotonic_s); waive only for "
                    "timestamps that cross process boundaries"))
        return out


# ---------------------------------------------------------------------------
class RetryDeadline(Rule):
    name = "retry-deadline"
    doc = ("a retry loop (sleep + broad exception handler) must "
           "exclude DEADLINE_EXCEEDED and application errors from "
           "re-attempts — the budget died, not the peer (the PR-5 "
           "retry contract)")

    BROAD = frozenset({"Exception", "BaseException", "OSError",
                       "ConnectionError", "RpcError", "grpc.RpcError"})
    EXCLUDERS = frozenset({"DeadlineExceeded", "Cancelled",
                           "DEADLINE_EXCEEDED"})

    def _broad_handler(self, h: ast.ExceptHandler) -> bool:
        if h.type is None:
            return True
        types = (h.type.elts if isinstance(h.type, ast.Tuple)
                 else [h.type])
        return any(_dotted(t) in self.BROAD
                   or _dotted(t).rsplit(".", 1)[-1] in self.BROAD
                   for t in types)

    def check_file(self, ctx: FileContext) -> list[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            body = list(_walk_no_defs(node))
            has_sleep = any(
                isinstance(n, ast.Call)
                and _dotted(n.func).endswith("sleep")
                for n in body)
            broad = [n for n in body
                     if isinstance(n, ast.ExceptHandler)
                     and self._broad_handler(n)]
            if not (has_sleep and broad):
                continue
            names = {n.id for n in body if isinstance(n, ast.Name)}
            names |= {n.attr for n in body
                      if isinstance(n, ast.Attribute)}
            if not (names & self.EXCLUDERS):
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    "retry loop with a broad exception handler does "
                    "not exclude DEADLINE_EXCEEDED/DeadlineExceeded/"
                    "Cancelled — retries must never re-spend an "
                    "expired budget or re-apply an answered request"))
        return out


# ---------------------------------------------------------------------------
class MetricDocs(Rule):
    name = "metric-docs"
    doc = ("METRICS registrations use literal names and explicit "
           "label kwargs (the runtime cardinality guard bounds "
           "values; literals bound the NAME space), and every name "
           "has a backticked row in README's observability table")

    METHODS = frozenset({"inc", "observe", "set_gauge"})

    def __init__(self):
        self.names: set[str] = set()
        self.sites: list[dict] = []

    def applies(self, rel: str) -> bool:
        return rel.startswith("dgraph_tpu/") or rel == "bench.py"

    def check_file(self, ctx: FileContext) -> list[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self.METHODS
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "METRICS"):
                continue
            if not node.args or not (
                    isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    "metric name must be a string literal — a dynamic "
                    "name defeats both the README doc table and the "
                    "per-name cardinality guard"))
                continue
            name = node.args[0].value
            self.names.add(name)
            self.sites.append({"name": name, "kind": node.func.attr,
                               "file": ctx.rel, "line": node.lineno})
            if any(kw.arg is None for kw in node.keywords):
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    f"metric {name!r} expands a dynamic **label dict — "
                    f"label KEYS must be explicit kwargs so the label "
                    f"schema stays reviewable and bounded"))
        return out

    def finalize(self, analyzer) -> list[Finding]:
        from dgraph_tpu.utils.metrics import DROPPED_SERIES
        names = self.names | {DROPPED_SERIES}
        readme = analyzer.readme_text
        missing = sorted(n for n in names if f"`{n}" not in readme)
        if not missing:
            return []
        # message preserved verbatim from the PR-4 doc-lint
        # (tests/test_metrics.py) it subsumes
        return [Finding(
            self.name, "README.md", 1,
            f"metric name(s) emitted but undocumented in README's "
            f"observability table: {missing}")]


# ---------------------------------------------------------------------------
class JitPurity(Rule):
    name = "jit-purity"
    doc = ("functions handed to jax.jit stay pure: no `.item()`/"
           "`.tolist()` host syncs, no numpy host ops, no Python "
           "branches on tracer params (branch on static_argnames or "
           "use jnp.where) — an impure jit path either retraces per "
           "call or hard-faults on TPU")

    HOST_SYNCS = frozenset({"item", "tolist"})

    def _jitted_functions(self, tree: ast.Module):
        """(FunctionDef, static_argnames) for every function that ends
        up inside jax.jit: decorated directly, decorated via
        functools.partial(jax.jit, ...), or passed by name to a
        jax.jit(fn, ...) call anywhere in the module."""
        jit_by_name: dict[str, set[str]] = {}
        wrappers = ("jax.jit", "jit", "jax.shard_map", "shard_map",
                    "jax.pmap", "pmap", "pjit", "jax.experimental."
                    "shard_map.shard_map")
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and _dotted(node.func) in wrappers
                    and node.args
                    and isinstance(node.args[0], ast.Name)):
                jit_by_name[node.args[0].id] = self._statics(node)
        for node in ast.walk(tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            for dec in node.decorator_list:
                if _dotted(dec) in ("jax.jit", "jit"):
                    yield node, set()
                    break
                if (isinstance(dec, ast.Call)
                        and _dotted(dec.func) == "functools.partial"
                        and dec.args
                        and _dotted(dec.args[0]) in ("jax.jit", "jit")):
                    yield node, self._statics(dec)
                    break
            else:
                if node.name in jit_by_name:
                    yield node, jit_by_name[node.name]

    @staticmethod
    def _statics(call: ast.Call) -> set[str]:
        for kw in call.keywords:
            if kw.arg in ("static_argnames", "static_argnums"):
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value,
                                                              str):
                    return {v.value}
                if isinstance(v, (ast.Tuple, ast.List)):
                    return {e.value for e in v.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)}
        return set()

    @staticmethod
    def _tracer_params(fn: ast.FunctionDef, statics: set[str]):
        """Param names that are tracers at trace time: not static, and
        not optional-None structure flags (default None ⇒ branching on
        them is a static pytree-structure decision)."""
        args = list(fn.args.posonlyargs) + list(fn.args.args)
        defaults = [None] * (len(args) - len(fn.args.defaults)) \
            + list(fn.args.defaults)
        out = set()
        for a, d in zip(args, defaults):
            if a.arg in statics or a.arg == "self":
                continue
            if isinstance(d, ast.Constant) and d.value is None:
                continue
            out.add(a.arg)
        for a, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
            if a.arg in statics:
                continue
            if isinstance(d, ast.Constant) and d.value is None:
                continue
            out.add(a.arg)
        return out

    def _branch_names(self, test: ast.AST) -> set[str]:
        """Names a branch test DYNAMICALLY depends on: excludes
        `x is None` comparisons and names only reached through
        `len(...)` / `.shape` / `.ndim` / `.dtype` (static under
        tracing)."""
        skip: set[int] = set()
        for n in ast.walk(test):
            if (isinstance(n, ast.Compare)
                    and all(isinstance(op, (ast.Is, ast.IsNot))
                            for op in n.ops)):
                skip.update(id(x) for x in ast.walk(n))
            if (isinstance(n, ast.Call) and _dotted(n.func) == "len"):
                skip.update(id(x) for x in ast.walk(n))
            if (isinstance(n, ast.Attribute)
                    and n.attr in ("shape", "ndim", "dtype", "size")):
                skip.update(id(x) for x in ast.walk(n))
        return {n.id for n in ast.walk(test)
                if isinstance(n, ast.Name) and id(n) not in skip}

    def check_file(self, ctx: FileContext) -> list[Finding]:
        out = []
        for fn, statics in self._jitted_functions(ctx.tree):
            tracers = self._tracer_params(fn, statics)
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in self.HOST_SYNCS):
                    out.append(Finding(
                        self.name, ctx.rel, node.lineno,
                        f"host sync .{node.func.attr}() inside jitted "
                        f"function {fn.name}() — blocks dispatch and "
                        f"faults under trace"))
                elif (isinstance(node, ast.Call)
                        and _dotted(node.func).startswith("np.")):
                    out.append(Finding(
                        self.name, ctx.rel, node.lineno,
                        f"numpy host op {_dotted(node.func)}() inside "
                        f"jitted function {fn.name}() — runs on host "
                        f"per trace, not on device"))
                elif isinstance(node, (ast.If, ast.While)):
                    hot = self._branch_names(node.test) & tracers
                    if hot:
                        out.append(Finding(
                            self.name, ctx.rel, node.lineno,
                            f"Python branch on tracer param(s) "
                            f"{sorted(hot)} inside jitted function "
                            f"{fn.name}() — declare static_argnames "
                            f"or use jnp.where/lax.cond"))
        return out


# ---------------------------------------------------------------------------
class ShardMapCompat(Rule):
    name = "shard-map-compat"
    doc = ("`shard_map` has moved across jax releases "
           "(jax.experimental.shard_map.shard_map with check_rep → "
           "jax.shard_map with check_vma); utils/jaxcompat.py resolves "
           "it ONCE per process and is the only file allowed to touch "
           "either spelling — everywhere else imports the shim, so a "
           "jax upgrade can't silently re-park the mesh layer")

    SHIM = "dgraph_tpu/utils/jaxcompat.py"

    def applies(self, rel: str) -> bool:
        return ((rel.startswith("dgraph_tpu/") or rel == "bench.py")
                and rel != self.SHIM)

    def check_file(self, ctx: FileContext) -> list[Finding]:
        out = []
        flagged: set[int] = set()  # one finding per line, not per
        #                            nested Attribute of the same chain

        def flag(line: int, what: str) -> None:
            if line in flagged:
                return
            flagged.add(line)
            out.append(Finding(
                self.name, ctx.rel, line,
                f"direct {what} outside utils/jaxcompat.py — import "
                f"the versioned resolver instead "
                f"(from dgraph_tpu.utils.jaxcompat import shard_map)"))

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute):
                d = _dotted(node)
                if (d == "jax.shard_map"
                        or d.startswith("jax.experimental.shard_map")):
                    flag(node.lineno, f"`{d}` reference")
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod.startswith("jax.experimental.shard_map") or (
                        mod == "jax" and any(a.name == "shard_map"
                                             for a in node.names)):
                    flag(node.lineno, f"import from `{mod}`")
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.startswith("jax.experimental.shard_map"):
                        flag(node.lineno, f"import of `{a.name}`")
        return out


# ---------------------------------------------------------------------------
class FusedHostCallback(Rule):
    name = "fused-host-callback"
    doc = ("R13: jitted functions in the fused-program layer "
           "(engine/fused.py, ops/) must keep host accounting OUT of "
           "the traced region — a costprofile/tracing/METRICS/"
           "jit_call/deadline call inside runs once at trace time "
           "(then silently never again on cached executions) or drags "
           "a host round-trip into the single-launch program; account "
           "around the dispatch, never inside it")

    SCOPES = ("dgraph_tpu/ops/",)
    HOST_HELPERS = ("costprofile", "tracing", "METRICS", "deadline")
    HOST_CALLS = frozenset({"jit_call", "note_launch", "launch_frame"})

    def applies(self, rel: str) -> bool:
        return (rel.startswith(self.SCOPES)
                or rel == "dgraph_tpu/engine/fused.py")

    def check_file(self, ctx: FileContext) -> list[Finding]:
        out = []
        for fn, _statics in JitPurity()._jitted_functions(ctx.tree):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func)
                root = d.split(".", 1)[0]
                leaf = d.rsplit(".", 1)[-1]
                if root in self.HOST_HELPERS or leaf in self.HOST_CALLS:
                    out.append(Finding(
                        self.name, ctx.rel, node.lineno,
                        f"host accounting call {d}() inside jitted "
                        f"function {fn.name}() — it runs at trace "
                        f"time only; move it outside the traced "
                        f"region (around the dispatch site)"))
        return out


# ---------------------------------------------------------------------------
class AtomicWrite(Rule):
    name = "atomic-write"
    doc = ("persistence-layer files (store/, server/backup.py) must be "
           "written via the tmp+fsync+os.replace pattern "
           "(vault.atomic_write / write_bytes, or a function that "
           "itself fsyncs and replaces) — a kill mid-`open(..., 'w')` "
           "leaves a torn file where recovery expects a whole one")

    SCOPES = ("dgraph_tpu/store/",)

    def applies(self, rel: str) -> bool:
        return (rel.startswith(self.SCOPES)
                or rel == "dgraph_tpu/server/backup.py")

    @staticmethod
    def _atomic_spans(tree: ast.Module) -> list[tuple[int, int]]:
        """Line spans of functions that ARE the atomic pattern: they
        call both os.fsync and os.replace themselves, so their write
        handle is the tmp side of a replace."""
        spans = []
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            calls = {_dotted(n.func) for n in ast.walk(node)
                     if isinstance(n, ast.Call)}
            if "os.replace" in calls and "os.fsync" in calls:
                spans.append((node.lineno,
                              getattr(node, "end_lineno", node.lineno)))
        return spans

    def check_file(self, ctx: FileContext) -> list[Finding]:
        out = []
        spans = self._atomic_spans(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and _dotted(node.func) == "open"):
                continue
            mode = None
            if len(node.args) >= 2 and isinstance(node.args[1],
                                                  ast.Constant):
                mode = node.args[1].value
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value,
                                                   ast.Constant):
                    mode = kw.value.value
            if not (isinstance(mode, str) and mode.startswith("w")):
                continue  # reads/appends ("r", "rb", "ab", "r+b") pass
            if any(lo <= node.lineno <= hi for lo, hi in spans):
                continue
            out.append(Finding(
                self.name, ctx.rel, node.lineno,
                f"non-atomic file write open(..., {mode!r}) in the "
                f"persistence layer — route it through "
                f"vault.atomic_write/write_bytes (tmp+fsync+"
                f"os.replace), or waive with the reason a torn file "
                f"is safe here"))
        return out


# ---------------------------------------------------------------------------
class CacheRegistration(Rule):
    name = "cache-registration"
    doc = ("R14: byte-holding caches must join the process memory "
           "governor (utils/memgov.py) — every `Memo(...)` call "
           "carries an explicit `governed=` decision, and a file that "
           "creates a dict-typed `*_cache` attribute must call "
           "`memgov.GOVERNOR.register` somewhere (or waive with the "
           "reason its bytes are bounded); an unregistered cache is "
           "invisible to the OOM evict-retry path and /debug/memory")

    DICT_CTORS = frozenset({"dict", "OrderedDict",
                            "collections.OrderedDict"})

    def applies(self, rel: str) -> bool:
        # the governor itself and the Memo implementation are the
        # mechanism, not clients of it
        return (rel.startswith("dgraph_tpu/")
                and rel not in ("dgraph_tpu/utils/memgov.py",
                                "dgraph_tpu/utils/jitcache.py"))

    @staticmethod
    def _is_dict_value(node: ast.AST) -> bool:
        if isinstance(node, ast.Dict):
            return True
        return (isinstance(node, ast.Call)
                and _dotted(node.func)
                in CacheRegistration.DICT_CTORS)

    @staticmethod
    def _cache_targets(node: ast.stmt):
        """Attribute/name targets ending in `_cache` of an assignment
        whose value is a dict literal / dict() / OrderedDict()."""
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            return
        if not CacheRegistration._is_dict_value(value):
            return
        for t in targets:
            if isinstance(t, ast.Attribute) and t.attr.endswith("_cache"):
                yield t.attr
            elif isinstance(t, ast.Name) and t.id.endswith("_cache"):
                yield t.id

    def check_file(self, ctx: FileContext) -> list[Finding]:
        out = []
        registers = any(
            isinstance(n, ast.Call)
            and _dotted(n.func).endswith("GOVERNOR.register")
            for n in ast.walk(ctx.tree))
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and _dotted(node.func).rsplit(".", 1)[-1] == "Memo"
                    and not any(kw.arg == "governed"
                                for kw in node.keywords)):
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    "Memo(...) without an explicit governed= decision "
                    "— pass governed=\"<inventory name>\" to join the "
                    "memory governor, or governed=None with a waiver "
                    "stating why its bytes stay unbudgeted"))
            elif isinstance(node, ast.stmt) and not registers:
                for attr in self._cache_targets(node):
                    out.append(Finding(
                        self.name, ctx.rel, node.lineno,
                        f"dict-typed cache attribute `{attr}` in a "
                        f"file that never calls "
                        f"memgov.GOVERNOR.register — register its "
                        f"bytes/evict callbacks (GOVERNED_CACHES "
                        f"inventory), or waive with the bound that "
                        f"keeps it small"))
        return out


# ---------------------------------------------------------------------------
class SloSpec(Rule):
    name = "slo-spec"
    doc = ("R15: SLO objective names stay inside the utils/slo."
           "SLO_SPECS inventory — a literal `slo=` metric label, a "
           "literal SLO_SPECS/DEFAULT_TARGETS subscript, or a literal "
           "`_evaluator(\"...\")` registration outside the inventory "
           "splits the burn-rate vocabulary between dashboards, "
           "/debug/slo, and the watchdog's kind=slo conviction feed")

    SPEC_TABLES = frozenset({"SLO_SPECS", "DEFAULT_TARGETS"})

    def __init__(self):
        # jax-free by design (utils/slo.py imports no jax), so the
        # static-analysis CLI can load the inventory directly
        from dgraph_tpu.utils.slo import SLO_SPECS
        self.known = frozenset(SLO_SPECS)

    def applies(self, rel: str) -> bool:
        return rel.startswith("dgraph_tpu/") or rel == "bench.py"

    def check_file(self, ctx: FileContext) -> list[Finding]:
        out = []

        def flag(line: int, name: str, where: str) -> None:
            out.append(Finding(
                self.name, ctx.rel, line,
                f"SLO name {name!r} ({where}) is not in the "
                f"utils/slo.SLO_SPECS inventory — add it there with a "
                f"doc line (and an @_evaluator), or fix the literal; "
                f"known: {sorted(self.known)}"))

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if (kw.arg == "slo"
                            and isinstance(kw.value, ast.Constant)
                            and isinstance(kw.value.value, str)
                            and kw.value.value not in self.known):
                        flag(node.lineno, kw.value.value,
                             "literal slo= label")
                if (_dotted(node.func).rsplit(".", 1)[-1]
                        == "_evaluator"
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)
                        and node.args[0].value not in self.known):
                    flag(node.lineno, node.args[0].value,
                         "evaluator registration")
            elif (isinstance(node, ast.Subscript)
                    and _dotted(node.value).rsplit(".", 1)[-1]
                    in self.SPEC_TABLES
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                    and node.slice.value not in self.known):
                flag(node.lineno, node.slice.value, "spec-table lookup")
        return out


def default_rules() -> list[Rule]:
    from dgraph_tpu.analysis.guards import guard_rules
    return [HotLoopCheckpoint(), DirectIO(), WallClock(),
            RetryDeadline(), MetricDocs(), JitPurity(),
            ShardMapCompat(), FusedHostCallback(),
            AtomicWrite(), CacheRegistration(),
            SloSpec()] + guard_rules()
