"""graftrace: lock-discipline inference + rules R9–R12 (Eraser, static half).

PR 6's lock-order sanitizer catches deadlocks; this module catches the
OTHER classic concurrency failure of a serving stack — an unguarded
read/write of shared mutable state. It is the static half of the
Eraser lockset story (Savage et al., "Eraser: A Dynamic Data Race
Detector for Multithreaded Programs"): per class, infer which
`self._field` attributes the code treats as guarded by which
`make_lock`/`make_rlock`/`make_condition` lock, then hold every other
access site to that discipline. The dynamic half (`utils/locks.py`
`guarded()` + `DGRAPH_TPU_RACE_SANITIZER=1`) arms the SAME inventory
at runtime — `runtime_inventory()` below is its single source of
truth, so the two halves cannot drift (tests/test_lint.py pins the
round-trip).

Inference, per class:

* **lock attrs** — `self.X = locks.make_lock("name")` (f-string names
  keep their literal parts, dynamic pieces become `*`:
  `admission.*`).
* **lock scopes** — `with self.X:` bodies, without descending into
  nested function definitions (a closure runs on another thread).
* **helper propagation** — a method called ONLY from inside lock-X
  scopes of its own class inherits X as held context (the
  `_publish()` "caller holds the lock" idiom), to a fixpoint.
* **writes** — rebinds (`self.F = …`, `self.F += …`), subscript
  stores/deletes (`self.F[k] = …`), and calls of known mutators
  (`self.F.append(…)`, `.update`, `.pop`, …). Everything else that
  touches `self.F` is a read.
* **discipline** — a field is guarded by lock X when it has ≥1 write
  under X AND a clear majority (≥ 3/4) of its access sites hold X —
  the RacerX-style belief step. The majority bar matters: the
  codebase's other legitimate pattern is the atomic published
  pointer (`self.mvcc` REBOUND under `alpha.apply`, read unlocked on
  every query — CPython reference loads are atomic and readers
  tolerate either snapshot), where the lock serializes WRITERS only;
  a naive "one locked write ⇒ every access locked" rule would drown
  the real findings in ~100 waivers for that pattern alone.
* **init window** — `__init__`/`__del__`, and any method reachable
  ONLY from them (`ZeroState._replay`, boot-time rebuilds), run
  before the object is shared (Eraser's initialization state) and
  are exempt.

Rules (same waiver grammar, same CLI, same tier-1 gate as R1–R8):

R9  guarded-field          a field written under a lock at any site
                           must hold that lock at EVERY access site
                           in the class — an unguarded access is the
                           read/write race `go test -race` would
                           flag.
R10 guarded-escape         returning/yielding a bare reference to a
                           mutable guarded container (list/dict/set/
                           deque field) from inside its lock scope —
                           the caller mutates/iterates it unlocked;
                           return a copy or a snapshot.
R11 split-critical-section a read of a guarded field in one lock
                           scope feeding a write of the same field in
                           a SEPARATE acquisition within one function
                           (check-then-act across a lock release) —
                           revalidate under the second acquisition or
                           fuse the sections, and say which in a
                           waiver.
R12 untracked-lock         direct `threading.Lock()`/`RLock()`/
                           `Condition()` construction outside
                           utils/locks.py — a lock both sanitizers
                           cannot see guards nothing, as far as the
                           race story is concerned.

All four are deliberately HEURISTICS with the mandatory-reason waiver
escape hatch: aliasing (`buf = self._spans`), cross-object discipline
and lock hand-offs are invisible to a per-class AST pass — that is
what the dynamic half is for. A field whose R9 finding is WAIVED
(reasoned benign) is also dropped from `runtime_inventory()`, so one
reviewed reason disarms both halves for that field instead of the
dynamic gate re-litigating it every run.
"""

from __future__ import annotations

import ast
import dataclasses
import functools
import pathlib

from dgraph_tpu.analysis import FileContext, Finding, Rule

__all__ = ["ClassGuards", "infer_module", "runtime_inventory",
           "GuardedField", "GuardedEscape", "SplitCriticalSection",
           "UntrackedLock", "guard_rules"]

_LOCK_FACTORIES = {"make_lock": "lock", "make_rlock": "rlock",
                   "make_condition": "condition"}

# method calls that mutate their receiver: `self.F.append(x)` is a
# WRITE of F's guarded state even though the binding only loads
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popleft", "popitem", "remove",
    "discard", "clear", "sort", "reverse", "rotate", "write"})

# container constructors: a field initialized from one of these is a
# mutable container whose reference must not escape its lock scope
_CONTAINER_CALLS = frozenset({
    "list", "dict", "set", "deque", "defaultdict", "OrderedDict",
    "Counter", "bytearray", "collections.deque",
    "collections.defaultdict", "collections.OrderedDict",
    "collections.Counter"})

_INIT_METHODS = ("__init__", "__del__", "__init_subclass__")

# the belief bar: a lock "protects" a field when at least 3/4 of the
# field's access sites hold it (and at least one of those is a write)
_BELIEF_NUM = 0.75


def _dotted(node: ast.AST) -> str:
    from dgraph_tpu.analysis.rules import _dotted as d
    return d(node)


def _is_self_attr(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self")


def _lock_label(call: ast.Call) -> str:
    """The lock's order-class name: the literal first argument, or an
    f-string's literal parts with `*` for each dynamic piece
    (`f"admission.{name}"` → "admission.*")."""
    if call.args:
        a = call.args[0]
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return a.value
        if isinstance(a, ast.JoinedStr):
            return "".join(
                v.value if (isinstance(v, ast.Constant)
                            and isinstance(v.value, str)) else "*"
                for v in a.values)
    return "?"


def _is_container_value(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        d = _dotted(node.func)
        return d in _CONTAINER_CALLS or d.rsplit(".", 1)[-1] in (
            "deque", "defaultdict", "OrderedDict", "Counter")
    return False


@dataclasses.dataclass
class _Access:
    """One `self.F` touch: where, read or write, which lock scopes
    enclosed it (attr → id of the innermost `with` node per lock),
    and which method it sits in."""

    field: str
    write: bool
    line: int
    scopes: dict  # lock_attr -> id(with_node)
    method: str


@dataclasses.dataclass
class ClassGuards:
    """Everything the rules (and the runtime shim) need for one
    class."""

    name: str
    file: str
    line: int
    locks: dict          # lock attr -> order-class label
    accesses: list       # [_Access]
    containers: set      # fields initialized as mutable containers
    methods: set         # method names (to skip `self.m()` "reads")

    def held_at(self, acc: _Access) -> set:
        """Lock attrs effectively held at an access: direct `with`
        scopes plus the method's propagated caller context."""
        return set(acc.scopes) | self.method_ctx.get(acc.method, set())

    def in_init_window(self, acc: _Access) -> bool:
        return (acc.method in _INIT_METHODS
                or acc.method in self.init_exempt)

    # filled by infer_module after the propagation fixpoints
    method_ctx: dict = dataclasses.field(default_factory=dict)
    init_exempt: set = dataclasses.field(default_factory=set)

    def discipline(self) -> dict:
        """The inferred lock discipline: lock attr → {field:
        (locked_accesses, unlocked_accesses)} for every field that
        clears the belief bar — ≥1 write under the lock and ≥ 3/4 of
        its (non-init-window) access sites holding it. The unlocked
        minority are the R9 findings and the reason the dynamic
        sanitizer would fire."""
        per_field: dict = {}
        for a in self.accesses:
            if self.in_init_window(a):
                continue
            per_field.setdefault(a.field, []).append(a)
        out: dict = {x: {} for x in self.locks}
        for field, accs in per_field.items():
            for x in self.locks:
                locked = [a for a in accs if x in self.held_at(a)]
                unlocked = [a for a in accs if x not in self.held_at(a)]
                if not any(a.write for a in locked):
                    continue
                if len(locked) < _BELIEF_NUM * (len(locked)
                                                + len(unlocked)):
                    continue
                out[x][field] = (locked, unlocked)
        return out

    def guarded_fields(self) -> dict:
        """lock attr -> every field touched under it (read or
        write) — the superset R10/R11 key off."""
        out: dict = {x: set() for x in self.locks}
        for a in self.accesses:
            for x in self.held_at(a):
                out[x].add(a.field)
        return out


def _walk_no_defs(node: ast.AST):
    todo = list(ast.iter_child_nodes(node))
    while todo:
        n = todo.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            todo.extend(ast.iter_child_nodes(n))


def _parents(fn: ast.AST) -> dict:
    par = {}
    for node in ast.walk(fn):
        for child in ast.iter_child_nodes(node):
            par[id(child)] = node
    return par


def _classify(node: ast.Attribute, par: dict) -> bool:
    """Is this `self.F` node a WRITE of F's state? Rebinds, subscript
    stores/deletes through it, and mutator-method calls on it all
    count."""
    if isinstance(node.ctx, (ast.Store, ast.Del)):
        return True
    p = par.get(id(node))
    if (isinstance(p, ast.Subscript) and p.value is node
            and isinstance(p.ctx, (ast.Store, ast.Del))):
        return True
    if (isinstance(p, ast.Attribute) and p.value is node
            and p.attr in _MUTATORS):
        g = par.get(id(p))
        if isinstance(g, ast.Call) and g.func is p:
            return True
    return False


def _scan_method(fn: ast.FunctionDef, lock_attrs: set,
                 method_names: set):
    """Walk one method, carrying the set of enclosing lock scopes.
    Yields (accesses, call_sites) where call_sites is
    [(callee, scopes_dict)] for intra-class `self.m()` calls."""
    par = _parents(fn)
    accesses: list[_Access] = []
    calls: list[tuple] = []

    def visit(node, scopes):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return  # another execution context (often another thread)
        if isinstance(node, ast.With):
            inner = dict(scopes)
            for item in node.items:
                ce = item.context_expr
                visit(ce, scopes)
                if item.optional_vars is not None:
                    visit(item.optional_vars, scopes)
                if _is_self_attr(ce) and ce.attr in lock_attrs:
                    inner[ce.attr] = id(node)
            for stmt in node.body:
                visit(stmt, inner)
            return
        if _is_self_attr(node):
            p = par.get(id(node))
            is_call = isinstance(p, ast.Call) and p.func is node
            if node.attr in lock_attrs:
                pass  # the lock itself, not guarded state
            elif is_call and node.attr in method_names:
                calls.append((node.attr, dict(scopes)))
            elif not node.attr.startswith("__"):
                accesses.append(_Access(
                    node.attr, _classify(node, par), node.lineno,
                    dict(scopes), fn.name))
        for child in ast.iter_child_nodes(node):
            visit(child, scopes)

    for stmt in fn.body:
        visit(stmt, {})
    return accesses, calls


def infer_module(tree: ast.Module, rel: str) -> list[ClassGuards]:
    """Lock-discipline inference over every top-level class of one
    module (nested classes are scanned too, under their own name)."""
    out = []
    for cls in [n for n in ast.walk(tree)
                if isinstance(n, ast.ClassDef)]:
        methods = {n.name: n for n in cls.body
                   if isinstance(n, ast.FunctionDef)}
        locks: dict = {}
        containers: set = set()
        for fn in methods.values():
            for node in _walk_no_defs(fn):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                for tgt in node.targets:
                    if not _is_self_attr(tgt):
                        continue
                    leaf = _dotted(node.value.func).rsplit(".", 1)[-1]
                    if leaf in _LOCK_FACTORIES:
                        locks[tgt.attr] = _lock_label(node.value)
        if not locks:
            continue
        # container-ness: any `self.F = <container literal/ctor>`
        for fn in methods.values():
            for node in _walk_no_defs(fn):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if (_is_self_attr(tgt)
                                and _is_container_value(node.value)):
                            containers.add(tgt.attr)
        cg = ClassGuards(cls.name, rel, cls.lineno, locks, [],
                         containers, set(methods))
        call_sites: dict = {}   # callee -> [(caller, scope_lockset)]
        for name, fn in methods.items():
            accs, calls = _scan_method(fn, set(locks), set(methods))
            cg.accesses.extend(accs)
            for callee, scopes in calls:
                call_sites.setdefault(callee, []).append(
                    (name, set(scopes)))
        # init-window fixpoint FIRST: a method reachable ONLY from
        # __init__/__del__ (transitively) runs before the object is
        # shared — optimistic start, shrink to the fixed point
        exempt = {m for m in methods
                  if m in call_sites and m not in _INIT_METHODS}
        changed = True
        while changed:
            changed = False
            for m in list(exempt):
                if not all(c in _INIT_METHODS or c in exempt
                           for c, _held in call_sites[m]):
                    exempt.discard(m)
                    changed = True
        cg.init_exempt = exempt
        # helper-propagation fixpoint: ctx[m] = ∩ over call sites of
        # (locks held at the site ∪ ctx[caller]); methods with no
        # intra-class call site are entry points (ctx = ∅). Init-
        # window call sites are skipped — an __init__ caller cannot
        # race, so `_replay` (boot replay unlocked, runtime replay
        # under the lock) still counts as lock-context. Sets only
        # shrink from the optimistic start, so this converges.
        ctx = {m: (set(locks) if m in call_sites else set())
               for m in methods}
        for m in _INIT_METHODS:
            ctx[m] = set()  # constructors are entry points, always
        changed = True
        while changed:
            changed = False
            for m, sites in call_sites.items():
                if m in _INIT_METHODS:
                    continue
                live = [(c, held) for c, held in sites
                        if c not in _INIT_METHODS and c not in exempt]
                if not live:
                    continue  # init-only: covered by init_exempt
                new = set(locks)
                for caller, held in live:
                    new &= held | ctx.get(caller, set())
                if new != ctx[m]:
                    ctx[m] = new
                    changed = True
        cg.method_ctx = ctx
        out.append(cg)
    return out


# ---------------------------------------------------------------------------
class GuardedField(Rule):
    name = "guarded-field"
    doc = ("a field the class demonstrably treats as lock-guarded "
           "(≥1 locked write, ≥3/4 of access sites locked) must hold "
           "that lock at EVERY access site — each unguarded minority "
           "site is a data race under the right interleaving; fix it "
           "or waive with the reason the access is benign "
           "(`__init__`-only methods and helpers called only under "
           "the lock are already exempt)")

    def check_file(self, ctx: FileContext) -> list[Finding]:
        out = []
        for cg in infer_module(ctx.tree, ctx.rel):
            seen: set = set()
            for attr, fields in cg.discipline().items():
                for field, (locked, unlocked) in fields.items():
                    for a in unlocked:
                        key = (field, a.line)
                        if key in seen:
                            continue
                        seen.add(key)
                        kind = "write" if a.write else "read"
                        out.append(Finding(
                            self.name, ctx.rel, a.line,
                            f"{cg.name}.{field} is guarded by lock "
                            f"{cg.locks[attr]!r} (self.{attr}) at "
                            f"{len(locked)} of "
                            f"{len(locked) + len(unlocked)} sites, "
                            f"but this {kind} in {a.method}() does "
                            f"not hold it — a data race under the "
                            f"right interleaving"))
        return out


# ---------------------------------------------------------------------------
class GuardedEscape(Rule):
    name = "guarded-escape"
    doc = ("returning/yielding a bare reference to a mutable guarded "
           "container field (list/dict/set/deque) from inside its "
           "lock scope hands callers state they will read/mutate "
           "UNLOCKED — return a copy or build a snapshot under the "
           "lock instead")

    # wrappers that still escape the bare reference when returned
    _TRANSPARENT = (ast.Tuple, ast.List, ast.Set)

    def _escapes(self, node: ast.AST, par: dict) -> bool:
        """Does this self.F reference flow into a Return/Yield
        through nothing but container literals? (`list(self.F)`,
        `self.F[k]`, `len(self.F)` all break the chain — they copy,
        index, or aggregate.)"""
        cur = node
        while True:
            p = par.get(id(cur))
            if p is None:
                return False
            if isinstance(p, (ast.Return, ast.Yield)):
                return True
            if isinstance(p, self._TRANSPARENT):
                cur = p
                continue
            if isinstance(p, ast.Dict) and cur in p.values:
                cur = p
                continue
            return False

    def check_file(self, ctx: FileContext) -> list[Finding]:
        out = []
        par = _parents(ctx.tree)
        for cg in infer_module(ctx.tree, ctx.rel):
            guarded = cg.guarded_fields()
            for a in cg.accesses:
                if a.write or a.field not in cg.containers:
                    continue
                holding = cg.held_at(a)
                if not holding:
                    continue
                if not any(a.field in guarded.get(x, ())
                           for x in holding):
                    continue
                # find the AST node at this site to test escape shape
                for node in ast.walk(ctx.tree):
                    if (_is_self_attr(node)
                            and node.attr == a.field
                            and node.lineno == a.line
                            and self._escapes(node, par)):
                        out.append(Finding(
                            self.name, ctx.rel, a.line,
                            f"{cg.name}.{a.field} is a mutable "
                            f"guarded container whose reference "
                            f"escapes its lock scope via "
                            f"return/yield — callers touch it "
                            f"unlocked; return a copy/snapshot"))
                        break
        return out


# ---------------------------------------------------------------------------
class SplitCriticalSection(Rule):
    name = "split-critical-section"
    doc = ("a guarded field read in one lock scope and written in a "
           "SEPARATE acquisition of the same lock within one "
           "function is check-then-act across a lock release — the "
           "state can change between the sections; fuse them or "
           "revalidate under the second acquisition (and waive with "
           "which one applies)")

    def check_file(self, ctx: FileContext) -> list[Finding]:
        out = []
        for cg in infer_module(ctx.tree, ctx.rel):
            by_method: dict = {}
            for a in cg.accesses:
                by_method.setdefault(a.method, []).append(a)
            for method, accs in by_method.items():
                if method in _INIT_METHODS:
                    continue
                for attr in cg.locks:
                    reads: dict = {}   # field -> first read line/scope
                    for a in sorted(accs, key=lambda x: x.line):
                        sid = a.scopes.get(attr)
                        if sid is None:
                            continue
                        if not a.write:
                            reads.setdefault(a.field, (a.line, sid))
                            continue
                        first = reads.get(a.field)
                        if first and first[1] != sid:
                            out.append(Finding(
                                self.name, ctx.rel, a.line,
                                f"{cg.name}.{a.field} read under "
                                f"{cg.locks[attr]!r} at line "
                                f"{first[0]} then written here in a "
                                f"SEPARATE acquisition — check-then-"
                                f"act across a lock release"))
                            reads.pop(a.field, None)
        return out


# ---------------------------------------------------------------------------
class UntrackedLock(Rule):
    name = "untracked-lock"
    doc = ("direct threading.Lock()/RLock()/Condition() construction "
           "outside utils/locks.py — only make_lock/make_rlock/"
           "make_condition locks are visible to the lock-order AND "
           "race sanitizers; an untracked lock guards nothing the "
           "tooling can check")

    HOME = "dgraph_tpu/utils/locks.py"
    BANNED = frozenset({"threading.Lock", "threading.RLock",
                        "threading.Condition"})

    def applies(self, rel: str) -> bool:
        return rel.startswith("dgraph_tpu/") and rel != self.HOME

    def check_file(self, ctx: FileContext) -> list[Finding]:
        out = []
        bare = {a.name for node in ast.walk(ctx.tree)
                if isinstance(node, ast.ImportFrom)
                and node.module == "threading"
                for a in node.names}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d in self.BANNED or (
                    d in ("Lock", "RLock", "Condition") and d in bare):
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    f"direct {d}() outside utils/locks.py — use "
                    f"locks.make_lock/make_rlock/make_condition so "
                    f"the lock-order and race sanitizers can see it"))
        return out


def guard_rules() -> list[Rule]:
    return [GuardedField(), GuardedEscape(), SplitCriticalSection(),
            UntrackedLock()]


# ---------------------------------------------------------------------------
# the runtime contract: ONE inventory for facts.py AND utils/locks.py

def class_inventory(ctx: FileContext) -> list[dict]:
    """Per-(class, lock) guarded-field entries for one scanned file:
    the fields with ≥1 locked write whose every unguarded access is a
    REAL (unwaived) finding. A field with a waived R9 finding is
    dropped — the reviewed reason disarms the static AND dynamic
    halves together, instead of the runtime gate re-flagging a benign
    pattern every run."""
    out = []
    for cg in infer_module(ctx.tree, ctx.rel):
        disc = cg.discipline()
        for attr in sorted(cg.locks):
            tracked = []
            for field, (_locked, unlocked) in disc[attr].items():
                if any(ctx.waiver_for(GuardedField.name, a.line)
                       is not None for a in unlocked):
                    continue  # reviewed-benign: disarm both halves
                tracked.append(field)
            if not tracked:
                continue
            out.append({"class": cg.name, "file": cg.file,
                        "line": cg.line, "lock": cg.locks[attr],
                        "lock_attr": attr,
                        "fields": sorted(tracked)})
    return out


@functools.lru_cache(maxsize=1)
def runtime_inventory() -> dict:
    """(repo-relative file, class name) → {"lock", "lock_attr",
    "fields"} over the whole package — what `locks.guarded()` arms at
    runtime. Cached: one source scan per process, first arm only.
    Classes with locks guarding several field groups merge under the
    FIRST lock attr per class in practice (one lock per class is the
    codebase norm); multi-lock classes get one entry per lock."""
    root = pathlib.Path(__file__).resolve().parents[2]
    inv: dict = {}
    for f in sorted((root / "dgraph_tpu").rglob("*.py")):
        if "__pycache__" in f.parts:
            continue
        rel = f.relative_to(root).as_posix()
        try:
            ctx = FileContext(rel, f.read_text())
        except SyntaxError:  # pragma: no cover - package parses clean
            continue
        for entry in class_inventory(ctx):
            key = (entry["file"], entry["class"])
            prev = inv.get(key)
            if prev is None:
                inv[key] = {"locks": {entry["lock_attr"]: {
                    "lock": entry["lock"],
                    "fields": tuple(entry["fields"])}}}
            else:
                prev["locks"][entry["lock_attr"]] = {
                    "lock": entry["lock"],
                    "fields": tuple(entry["fields"])}
    return inv
