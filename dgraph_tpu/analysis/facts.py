"""Facts inventory: the static half of the cost-model direction.

ROADMAP's TpuGraphs-style item needs per-query-shape cost priors built
from recorded compile/execute spans; matching a recorded span back to
the kernel that produced it needs a ground-truth inventory of what the
codebase can launch and measure. graftlint already parses every file,
so the same pass extracts:

* **kernels** — every function handed to `jax.jit` (with its
  static_argnames: the retrace axes, i.e. the cost-model's categorical
  features) and every `jit_call("<kernel>", key)` launch site (the
  names `jit_compile_us{kernel=}` series carry).
* **spans** — every `tracing.span("<name>", ...)` site: the vocabulary
  of the trace/OTLP streams the predictor trains on.
* **metrics** — every literal registration (name, kind, site).
* **locks** — every `make_lock/make_rlock/make_condition` order class,
  the static side of the lock sanitizer's graph.
* **guarded_fields** — the lock-discipline inventory (ISSUE 12,
  `guards.py`): per class, which fields are written under which lock —
  what rules R9–R11 enforce statically and what `locks.guarded()` arms
  dynamically under DGRAPH_TPU_RACE_SANITIZER=1. `guarded_sites` lists
  every runtime `guarded(self, …)` arming call, so test_lint.py can
  pin the static inventory and the dynamic registry to each other in
  BOTH directions (the `cost_record_fields` pattern).
* **cost_record_fields** — the runtime cost-record schema
  (utils/costprofile.FIELDS, re-exported verbatim): the static
  inventory and the runtime records SHARE this vocabulary, so a
  recorded cost joins back to the kernels/spans that incurred it
  (tests/test_lint.py pins the two in sync — the join key for the
  future learned cost model).
* **governed_caches** — the memory-governor cache inventory (ISSUE 16,
  utils/memgov.GOVERNED_CACHES): every byte-holding cache name the
  process-wide governor budgets, pinned both ways against the runtime
  registration surface; rule R14 enforces that new caches join it.
* **slo_specs** — the SLO objective inventory (ISSUE 17,
  utils/slo.SLO_SPECS): every service-level objective the burn-rate
  engine can evaluate, pinned both ways against the runtime evaluator
  registry; rule R15 keeps `slo=` label literals inside it.
* **fused_stage_kinds** — the whole-query fused-program inventory
  (ISSUE 15, engine/fused.STAGE_KINDS): every stage kind the plan
  compiler can emit into one jitted program, pinned both ways
  against the runtime stage-emitter registry. Rule R13 extends the
  R6 jit-purity facts to these programs: a jitted fused stage may
  not call costprofile/tracing/metrics host helpers in the traced
  region.

Emitted under `"facts"` in `--format=json` output.
"""

from __future__ import annotations

import ast

__all__ = ["extract_facts"]

_LOCK_FNS = {"make_lock": "lock", "make_rlock": "rlock",
             "make_condition": "condition"}


def _guarded_sites(ctx) -> list[dict]:
    """Every `locks.guarded(self, "<lock>")` arming call, tagged with
    its enclosing class — the dynamic registry's static footprint."""
    out = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for node in ast.walk(cls):
            if not (isinstance(node, ast.Call)
                    and _dotted(node.func).rsplit(".", 1)[-1]
                    == "guarded"
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id == "self"):
                continue
            lock = (node.args[1].value
                    if len(node.args) > 1
                    and isinstance(node.args[1], ast.Constant)
                    else "?")
            out.append({"class": cls.name, "file": ctx.rel,
                        "line": node.lineno, "lock": lock})
    return out


def _dotted(node: ast.AST) -> str:
    from dgraph_tpu.analysis.rules import _dotted as d
    return d(node)


def extract_facts(contexts) -> dict:
    from dgraph_tpu.analysis.guards import class_inventory
    from dgraph_tpu.analysis.rules import JitPurity

    kernels, launches, spans, locks = [], [], [], []
    metrics: list[dict] = []
    guarded_fields: list[dict] = []
    guarded_sites: list[dict] = []
    jit_rule = JitPurity()
    for ctx in contexts:
        if not (ctx.rel.startswith("dgraph_tpu/")
                or ctx.rel == "bench.py"):
            continue
        guarded_fields.extend(class_inventory(ctx))
        guarded_sites.extend(_guarded_sites(ctx))
        for fn, statics in jit_rule._jitted_functions(ctx.tree):
            kernels.append({
                "name": fn.name, "file": ctx.rel, "line": fn.lineno,
                "static_argnames": sorted(statics)})
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                leaf = d.rsplit(".", 1)[-1]
                arg0 = (node.args[0].value
                        if node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)
                        else None)
                if leaf == "jit_call" and arg0:
                    launches.append({"kernel": arg0, "file": ctx.rel,
                                     "line": node.lineno})
                elif leaf == "span" and arg0:
                    spans.append({"name": arg0, "file": ctx.rel,
                                  "line": node.lineno})
                elif leaf in _LOCK_FNS and arg0:
                    locks.append({"name": arg0,
                                  "kind": _LOCK_FNS[leaf],
                                  "file": ctx.rel,
                                  "line": node.lineno})
                elif (leaf in ("inc", "observe", "set_gauge") and arg0
                      and isinstance(node.func, ast.Attribute)
                      and isinstance(node.func.value, ast.Name)
                      and node.func.value.id == "METRICS"):
                    metrics.append({"name": arg0, "kind": leaf,
                                    "file": ctx.rel,
                                    "line": node.lineno})
    # ONE vocabulary: the runtime cost-record schema is imported, not
    # re-declared — facts and records cannot drift apart silently
    from dgraph_tpu.utils.costprofile import FIELDS as COST_FIELDS
    cost_fields = [{"name": n, "kind": d["kind"], "doc": d["doc"]}
                   for n, d in sorted(COST_FIELDS.items())]
    # same discipline for the PRIOR model's regressors (ISSUE 9): the
    # feature vocabulary utils/costprior.py fits on is re-exported
    # verbatim; tests/test_lint.py pins it both ways against FIELDS —
    # a prior can never train on a feature no record carries, and a
    # feature field can never silently fall out of the model's reach
    from dgraph_tpu.utils.costprior import FEATURES as PRIOR_FEATURES
    prior_features = [{"name": n, "kind": COST_FIELDS[n]["kind"]}
                      for n in PRIOR_FEATURES]
    # same discipline for the DEBUG SURFACE (ISSUE 13): the endpoint
    # inventory server/http.py keys its runtime dispatch on is
    # re-exported verbatim (import-free module, so the analysis CLI
    # never pulls the server's jax/grpc chain); tests/test_lint.py
    # pins inventory ↔ runtime route table in both directions
    from dgraph_tpu.server.debug_routes import DEBUG_ENDPOINTS
    debug_endpoints = [{"path": p, "doc": d}
                       for p, d in sorted(DEBUG_ENDPOINTS.items())]
    # same discipline for the WHOLE-QUERY FUSED PROGRAM (ISSUE 15):
    # the stage-kind inventory the plan compiler can emit
    # (engine/fused.STAGE_KINDS — a jax-free import by design) is
    # re-exported verbatim; tests/test_lint.py pins it against the
    # runtime stage-emitter registry in both directions, so a stage
    # the compiler emits but the inventory doesn't name (or an
    # inventoried kind no emitter serves) fails tier-1
    from dgraph_tpu.engine.fused import STAGE_KINDS
    fused_stages = [{"kind": k, "doc": d}
                    for k, d in sorted(STAGE_KINDS.items())]
    # same discipline for the MEMORY GOVERNOR (ISSUE 16): the static
    # inventory of governed cache names (utils/memgov.GOVERNED_CACHES —
    # a jax-free import by design) is re-exported verbatim;
    # tests/test_lint.py pins it both ways against the runtime
    # registration surface, so a cache that registers under an
    # uninventoried name (or an inventoried name nothing registers)
    # fails tier-1 — rule R14 enforces that byte-holding caches
    # register at all
    from dgraph_tpu.utils.memgov import GOVERNED_CACHES
    governed_caches = [{"name": n, "doc": d}
                       for n, d in sorted(GOVERNED_CACHES.items())]
    # same discipline for the SLO ENGINE (ISSUE 17): the objective
    # inventory (utils/slo.SLO_SPECS — a jax-free import by design) is
    # re-exported verbatim; tests/test_lint.py pins it both ways
    # against the runtime evaluator registry, so an objective with no
    # evaluator (or an evaluator for an un-inventoried name) fails
    # tier-1 — rule R15 enforces that `slo=` label literals and spec
    # lookups stay inside this vocabulary
    from dgraph_tpu.utils.slo import SLO_SPECS
    slo_specs = [{"name": n, "doc": d}
                 for n, d in sorted(SLO_SPECS.items())]
    return {
        "kernels": kernels,
        "kernel_launch_sites": launches,
        "span_sites": spans,
        "metric_sites": metrics,
        "lock_classes": locks,
        "guarded_fields": guarded_fields,
        "guarded_sites": guarded_sites,
        "cost_record_fields": cost_fields,
        "cost_prior_features": prior_features,
        "debug_endpoints": debug_endpoints,
        "fused_stage_kinds": fused_stages,
        "governed_caches": governed_caches,
        "slo_specs": slo_specs,
        "totals": {
            "kernels": len(kernels),
            "kernel_launch_sites": len(launches),
            "span_names": len({s["name"] for s in spans}),
            "metric_names": len({m["name"] for m in metrics}),
            "lock_classes": len({x["name"] for x in locks}),
            "guarded_classes": len({(g["file"], g["class"])
                                    for g in guarded_fields}),
            "guarded_fields": sum(len(g["fields"])
                                  for g in guarded_fields),
            "guarded_sites": len(guarded_sites),
            "cost_record_fields": len(cost_fields),
            "cost_prior_features": len(prior_features),
            "debug_endpoints": len(debug_endpoints),
            "fused_stage_kinds": len(fused_stages),
            "governed_caches": len(governed_caches),
            "slo_specs": len(slo_specs),
        },
    }
