"""CLI: `python -m dgraph_tpu.analysis [--format=text|json] [paths...]`.

Exit status 0 = no unwaived findings, 1 = findings (the build-failing
condition tier-1's tests/test_lint.py enforces), 2 = usage error.
Default scan set: the whole dgraph_tpu package + bench.py.

Second mode — the bench regression gate (ISSUE 17):
`--bench-compare OLD.json NEW.json [--bench-threshold 0.10]` diffs the
shared quality keys of two BENCH JSON documents (edges/s, latency
percentiles, kernel launches, shed precision) and exits 1 when any
drifts past the threshold in its bad direction. See compare.py.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from dgraph_tpu.analysis import Analyzer, default_paths


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dgraph_tpu.analysis",
        description="graftlint: AST invariant checker (rules R1-R12, "
                    "incl. the graftrace lock-discipline rules)")
    ap.add_argument("paths", nargs="*", type=pathlib.Path,
                    help="files/dirs to scan (default: the package "
                         "+ bench.py)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--show-waived", action="store_true",
                    help="text mode: also print waived findings")
    ap.add_argument("--facts", action="store_true",
                    help="text mode: print the facts inventory totals")
    ap.add_argument("--bench-compare", nargs=2,
                    metavar=("OLD.json", "NEW.json"),
                    help="bench regression gate: diff two BENCH JSON "
                         "files' shared quality keys; exit 1 past the "
                         "threshold (skips the lint scan)")
    ap.add_argument("--bench-threshold", type=float, default=0.10,
                    help="fractional drift in a key's bad direction "
                         "that fails the gate (default 0.10)")
    args = ap.parse_args(argv)

    if args.bench_compare:
        from dgraph_tpu.analysis.compare import bench_compare_main
        return bench_compare_main(args.bench_compare[0],
                                  args.bench_compare[1],
                                  args.bench_threshold, args.format)

    repo_root = pathlib.Path(__file__).resolve().parents[2]
    paths = args.paths or default_paths(repo_root)
    a = Analyzer(repo_root=repo_root)
    a.run(paths)

    if args.format == "json":
        print(json.dumps(a.to_json(), indent=2))
    else:
        for f in a.findings:
            if f.waived and not args.show_waived:
                continue
            print(f.format())
        counts = a.counts()
        print(f"graftlint: {len(a.unwaived())} finding(s), "
              f"{sum(counts['waived'].values())} waived, "
              f"{len(a.contexts)} file(s) scanned")
        if args.facts:
            print("facts:", json.dumps(a.facts["totals"]))
    return 1 if a.unwaived() else 0


if __name__ == "__main__":
    sys.exit(main())
