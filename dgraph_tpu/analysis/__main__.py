"""CLI: `python -m dgraph_tpu.analysis [--format=text|json] [paths...]`.

Exit status 0 = no unwaived findings, 1 = findings (the build-failing
condition tier-1's tests/test_lint.py enforces), 2 = usage error.
Default scan set: the whole dgraph_tpu package + bench.py.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from dgraph_tpu.analysis import Analyzer, default_paths


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dgraph_tpu.analysis",
        description="graftlint: AST invariant checker (rules R1-R12, "
                    "incl. the graftrace lock-discipline rules)")
    ap.add_argument("paths", nargs="*", type=pathlib.Path,
                    help="files/dirs to scan (default: the package "
                         "+ bench.py)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--show-waived", action="store_true",
                    help="text mode: also print waived findings")
    ap.add_argument("--facts", action="store_true",
                    help="text mode: print the facts inventory totals")
    args = ap.parse_args(argv)

    repo_root = pathlib.Path(__file__).resolve().parents[2]
    paths = args.paths or default_paths(repo_root)
    a = Analyzer(repo_root=repo_root)
    a.run(paths)

    if args.format == "json":
        print(json.dumps(a.to_json(), indent=2))
    else:
        for f in a.findings:
            if f.waived and not args.show_waived:
                continue
            print(f.format())
        counts = a.counts()
        print(f"graftlint: {len(a.unwaived())} finding(s), "
              f"{sum(counts['waived'].values())} waived, "
              f"{len(a.contexts)} file(s) scanned")
        if args.facts:
            print("facts:", json.dumps(a.facts["totals"]))
    return 1 if a.unwaived() else 0


if __name__ == "__main__":
    sys.exit(main())
