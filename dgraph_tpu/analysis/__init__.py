"""graftlint: AST invariant checker for the dgraph_tpu stack.

Reference parity: the reference keeps a heavily-threaded distributed
system honest with Go's toolchain — `go vet`, custom analyzers, and the
race detector wired into CI. Our Python/JAX port re-established the
same invariants PR by PR (deadline checkpoints in every hot loop, one
resilience wrapper for every outbound RPC, monotonic clocks in budget
arithmetic, retry policies that never re-spend an expired deadline,
bounded metric label spaces, jit-path purity) — but only as convention.
This package is the `go vet` analog: a pluggable AST lint framework
with codebase-specific rules (R1–R8 in `rules.py`; the graftrace
lock-discipline rules R9–R12 in `guards.py`), run by tier-1
(`tests/test_lint.py`) over the whole package so a perf refactor that
silently drops an invariant fails the build, not the next incident.

Waivers: a finding is suppressed by an inline comment on the offending
line or the line directly above it::

    # graftlint: allow(<rule>[, <rule>...]): <reason>

The reason string is MANDATORY — a reasonless waiver is itself a
finding (rule `waiver-syntax`). Waivers are the escape hatch for
intentional exceptions (a wall-clock timestamp that must cross process
boundaries, an O(log n) arithmetic loop); the reason is the review
record of WHY the invariant doesn't apply.

The analyzer also extracts a FACTS inventory (kernel shapes, span
sites, metric names, lock order classes — `facts.py`): the static half
of the ROADMAP's TpuGraphs-style cost-model item, and the input
`bench.py` folds into BENCH JSON so the perf trajectory tracks lint
debt alongside throughput.

Run standalone::

    python -m dgraph_tpu.analysis [--format=text|json] [paths...]
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re

__all__ = ["Finding", "FileContext", "Rule", "Analyzer", "run",
           "WAIVER_RE", "WAIVER_SYNTAX"]

WAIVER_RE = re.compile(
    r"#\s*graftlint:\s*allow\(\s*(?P<rules>[a-z0-9_,\s\-]+?)\s*\)"
    r"(?:\s*:\s*(?P<reason>\S.*))?")
WAIVER_SYNTAX = "waiver-syntax"


@dataclasses.dataclass
class Finding:
    """One rule violation at one site. `waived` findings are kept (the
    CLI can show them; bench counts them) but never fail the build."""

    rule: str
    path: str          # repo-relative, "/"-separated
    line: int
    msg: str
    waived: bool = False
    reason: str = ""   # the waiver's reason when waived

    def format(self) -> str:
        tag = f"  [waived: {self.reason}]" if self.waived else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.msg}{tag}"


class FileContext:
    """One scanned file: source, parsed tree, and its waiver map."""

    def __init__(self, rel: str, source: str):
        self.rel = rel
        self.source = source
        self.tree = ast.parse(source, filename=rel)
        self.lines = source.splitlines()
        # line number → (set of waived rules, reason, has_reason)
        self.waivers: dict[int, tuple[set[str], str, bool]] = {}
        for i, ln in enumerate(self.lines, start=1):
            m = WAIVER_RE.search(ln)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",")
                     if r.strip()}
            reason = (m.group("reason") or "").strip()
            self.waivers[i] = (rules, reason, bool(reason))
        self._effective = dict(self.waivers)
        for line, w in self.waivers.items():
            for ln in self._reach(line):
                self._effective.setdefault(ln, w)

    def _reach(self, line: int):
        """Lines a waiver at `line` covers beyond itself. A waiver on a
        comment-only line flows DOWN through the rest of its comment
        block to the next statement: the full span of a simple
        statement (a multi-line call keeps its finding on a
        continuation line), the header only of a compound one (a
        waiver above a `while` must not silence findings in its
        body). A trailing waiver on a code line covers that line."""
        if not self.lines[line - 1].lstrip().startswith("#"):
            return
        c = line + 1
        while c <= len(self.lines) and (
                not self.lines[c - 1].strip()
                or self.lines[c - 1].lstrip().startswith("#")):
            c += 1
        if c > len(self.lines):
            return
        best = None  # smallest statement span containing line c
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.stmt):
                continue
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= c <= end:
                if best is None or (end - node.lineno
                                    < best[1] - best[0].lineno):
                    body = getattr(node, "body", None)
                    hdr_end = (body[0].lineno - 1
                               if isinstance(body, list) and body
                               and isinstance(body[0], ast.stmt)
                               else end)
                    best = (node, end, hdr_end)
        if best is None:
            yield c
            return
        node, end, hdr_end = best
        lo = max(c, node.lineno)
        hi = hdr_end if hdr_end >= lo else end
        for ln in range(lo, hi + 1):
            yield ln

    def waiver_for(self, rule: str, line: int) -> str | None:
        """The reason string if `rule` is waived at `line` (same line,
        the line directly above, or within reach of a comment-block
        waiver), else None. A reasonless waiver does NOT waive — it
        surfaces as a `waiver-syntax` finding."""
        for ln in (line, line - 1):
            w = self._effective.get(ln)
            if w and rule in w[0] and w[2]:
                return w[1]
        return None


class Rule:
    """Base class: subclasses set `name`/`doc`, implement `check_file`,
    and may implement `finalize` for repo-level findings (rules that
    aggregate across files, like the metric-docs README pass)."""

    name = "base"
    doc = ""

    def applies(self, rel: str) -> bool:
        return rel.startswith("dgraph_tpu/")

    def check_file(self, ctx: FileContext) -> list[Finding]:
        return []

    def finalize(self, analyzer: "Analyzer") -> list[Finding]:
        return []


class Analyzer:
    """Drives a rule set over a file set; applies waivers; collects
    the facts inventory. `readme_text` is injectable for tests."""

    def __init__(self, rules: list[Rule] | None = None,
                 repo_root: pathlib.Path | None = None,
                 readme_text: str | None = None):
        if rules is None:
            from dgraph_tpu.analysis.rules import default_rules
            rules = default_rules()
        self.rules = rules
        self.repo_root = repo_root
        self._readme_text = readme_text
        self.contexts: list[FileContext] = []
        self.findings: list[Finding] = []
        self.facts: dict = {}

    @property
    def readme_text(self) -> str:
        if self._readme_text is None:
            p = ((self.repo_root or pathlib.Path(".")) / "README.md")
            self._readme_text = p.read_text() if p.exists() else ""
        return self._readme_text

    # -- scanning ------------------------------------------------------------
    def add_source(self, rel: str, source: str) -> None:
        ctx = FileContext(rel, source)
        self.contexts.append(ctx)
        for line, (rules, _reason, has_reason) in ctx.waivers.items():
            if not has_reason:
                self.findings.append(Finding(
                    WAIVER_SYNTAX, rel, line,
                    f"waiver for {sorted(rules)} carries no reason "
                    f"string — write `# graftlint: allow(rule): why`"))
        for rule in self.rules:
            if not rule.applies(rel):
                continue
            for f in rule.check_file(ctx):
                reason = ctx.waiver_for(f.rule, f.line)
                if reason is not None:
                    f.waived, f.reason = True, reason
                self.findings.append(f)

    def run(self, paths: list[pathlib.Path],
            repo_root: pathlib.Path | None = None) -> list[Finding]:
        """Scan files/trees under `paths`; then run repo-level
        finalizers and extract facts. Returns ALL findings (filter on
        `.waived` for the failing set)."""
        if repo_root is not None:
            self.repo_root = repo_root
        root = self.repo_root or pathlib.Path(".")
        files: list[pathlib.Path] = []
        for p in paths:
            if p.is_dir():
                files.extend(sorted(p.rglob("*.py")))
            elif p.suffix == ".py":
                files.append(p)
        for f in files:
            if "__pycache__" in f.parts:
                continue
            try:
                rel = f.resolve().relative_to(root.resolve()).as_posix()
            except ValueError:
                rel = f.as_posix()
            self.add_source(rel, f.read_text())
        self.finish()
        return self.findings

    def finish(self) -> None:
        """Repo-level passes: rule finalizers + the facts inventory."""
        for rule in self.rules:
            self.findings.extend(rule.finalize(self))
        from dgraph_tpu.analysis.facts import extract_facts
        self.facts = extract_facts(self.contexts)

    # -- reporting -----------------------------------------------------------
    def unwaived(self) -> list[Finding]:
        return [f for f in self.findings if not f.waived]

    def counts(self) -> dict[str, dict[str, int]]:
        """{"findings": {rule: unwaived}, "waived": {rule: waived}} —
        the shape bench.py embeds into BENCH JSON. Every active rule
        is pre-seeded at 0 so the BENCH trajectory shows a clean rule
        AS clean instead of omitting it (a new rule's debt is visible
        from its first run)."""
        out = {"findings": {r.name: 0 for r in self.rules},
               "waived": {r.name: 0 for r in self.rules}}
        for f in self.findings:
            bucket = "waived" if f.waived else "findings"
            out[bucket][f.rule] = out[bucket].get(f.rule, 0) + 1
        return out

    def to_json(self) -> dict:
        return {
            "findings": [dataclasses.asdict(f) for f in self.findings
                         if not f.waived],
            "waived": [dataclasses.asdict(f) for f in self.findings
                       if f.waived],
            "counts": self.counts(),
            "facts": self.facts,
        }


def default_paths(repo_root: pathlib.Path) -> list[pathlib.Path]:
    """What `python -m dgraph_tpu.analysis` (and tier-1) scans: the
    whole package, plus bench.py for the metric-docs pass."""
    paths = [repo_root / "dgraph_tpu"]
    bench = repo_root / "bench.py"
    if bench.exists():
        paths.append(bench)
    return paths


def run(repo_root: pathlib.Path | None = None) -> Analyzer:
    """One-call entry: scan the default file set with the default
    rules. Used by tests/test_lint.py and bench.py."""
    if repo_root is None:
        repo_root = pathlib.Path(__file__).resolve().parents[2]
    a = Analyzer(repo_root=repo_root)
    a.run(default_paths(repo_root), repo_root=repo_root)
    return a
