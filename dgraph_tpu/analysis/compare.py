"""Bench regression gate: diff two BENCH JSON documents, fail on drift.

`python -m dgraph_tpu.analysis --bench-compare OLD.json NEW.json`
flattens both documents to dotted-path -> number, keeps the paths BOTH
runs carry, and judges each watched path by its direction:

* throughput-like (`value` = edges/s, `shed_precision`) — a DROP past
  the threshold is a regression;
* latency/launch-like (any `*_us` percentile, `mean_kernel_launches`)
  — a RISE past the threshold is a regression.

Unwatched keys (stage wall-times, counters, configs) are ignored: they
are either noisy or not quality signals. Exit status mirrors the lint
CLI: 0 = within threshold, 1 = regression(s), 2 = unreadable input.
The comparison is pure arithmetic over the shared keys — no reruns, no
statistics — so it is deterministic given the two files and usable as
a CI gate between a base-branch bench artifact and the PR's.
"""

from __future__ import annotations

import json
import pathlib

__all__ = ["flatten", "direction", "compare", "bench_compare_main"]

# leaves where HIGHER is better (throughput / precision)
_HIGHER = frozenset({"value", "shed_precision", "edges_per_s",
                     "feature_bytes_per_s"})
# leaves where LOWER is better, beyond the `*_us` suffix rule
_LOWER = frozenset({"mean_kernel_launches", "launches_per_query"})


def flatten(doc, prefix: str = "") -> dict[str, float]:
    """BENCH JSON -> {dotted.path: number}. Non-numeric leaves and
    bools are dropped; list indices become path segments so repeated
    stages stay addressable."""
    out: dict[str, float] = {}
    if isinstance(doc, dict):
        for k, v in doc.items():
            out.update(flatten(v, f"{prefix}{k}."))
    elif isinstance(doc, list):
        for i, v in enumerate(doc):
            out.update(flatten(v, f"{prefix}{i}."))
    elif isinstance(doc, (int, float)) and not isinstance(doc, bool):
        out[prefix.rstrip(".")] = float(doc)
    return out


def direction(path: str) -> str | None:
    """'higher' / 'lower' for watched paths, None for ignored ones."""
    leaf = path.rsplit(".", 1)[-1]
    if leaf in _HIGHER:
        return "higher"
    if leaf in _LOWER or leaf.endswith("_us"):
        return "lower"
    return None


def compare(old: dict, new: dict,
            threshold: float = 0.10) -> list[dict]:
    """Per-shared-watched-key verdicts, regressions first. Each row:
    {key, direction, old, new, delta_frac, regressed}."""
    fo, fn = flatten(old), flatten(new)
    rows = []
    for key in sorted(set(fo) & set(fn)):
        d = direction(key)
        if d is None:
            continue
        ov, nv = fo[key], fn[key]
        delta = (nv - ov) / ov if ov else (0.0 if nv == ov else
                                           float("inf"))
        regressed = (delta > threshold if d == "lower"
                     else delta < -threshold)
        rows.append({"key": key, "direction": d, "old": ov, "new": nv,
                     "delta_frac": round(delta, 4)
                     if delta != float("inf") else delta,
                     "regressed": regressed})
    rows.sort(key=lambda r: (not r["regressed"], r["key"]))
    return rows


def bench_compare_main(old_path: str, new_path: str,
                       threshold: float, fmt: str = "text") -> int:
    try:
        old = json.loads(pathlib.Path(old_path).read_text())
        new = json.loads(pathlib.Path(new_path).read_text())
    except (OSError, ValueError) as e:
        print(f"bench-compare: cannot read input: {e}")
        return 2
    rows = compare(old, new, threshold)
    bad = [r for r in rows if r["regressed"]]
    if fmt == "json":
        print(json.dumps({"threshold": threshold, "rows": rows,
                          "regressions": len(bad)}, indent=2))
    else:
        for r in rows:
            mark = "REGRESSION" if r["regressed"] else "ok"
            print(f"{mark:>10}  {r['key']}  {r['old']:g} -> "
                  f"{r['new']:g}  ({r['delta_frac']:+.1%}, "
                  f"{r['direction']} is better)")
        print(f"bench-compare: {len(bad)} regression(s) past "
              f"{threshold:.0%} over {len(rows)} shared key(s)")
    return 1 if bad else 0
