"""CLI process entry: `python -m dgraph_tpu <subcommand>`.

Reference parity: `dgraph/cmd/root.go` cobra subcommands — `alpha` (data
server), `zero` (cluster oracle service), `live` / `bulk` (loaders),
`export`, `debug` (snapshot inspector), `version`. argparse stands in for
cobra; every flag maps onto the typed configs in utils/config.py.
"""

from __future__ import annotations

import argparse
import json
import sys

from dgraph_tpu import __version__
from dgraph_tpu.utils import logging as xlog
from dgraph_tpu.utils.config import AlphaConfig, load_config

# consecutive heartbeat failures before the loop escalates from a
# debug-level note to an ERROR log: a dead Zero link must be VISIBLE
# (a silent heartbeat failure eventually gets this alpha marked dead
# by Zero's liveness sweep with no local trace of why)
HEARTBEAT_ERROR_AFTER = 3


def run_heartbeat_loop(kind: str, interval_s: float, step, log,
                       stop=None) -> None:
    """Drive one heartbeat `step()` every `interval_s`, surviving
    failures — but never silently: every failure counts
    `heartbeat_failures_total{kind=}`, and `HEARTBEAT_ERROR_AFTER`
    consecutive failures escalate to an error-level log (once per
    outage, re-armed by the next success). `stop` (threading.Event)
    ends the loop — tests drive it; the CLI never sets it."""
    import threading

    from dgraph_tpu.utils.metrics import METRICS
    stop = stop or threading.Event()
    fails = 0
    while not stop.wait(interval_s):
        try:
            step()
            if fails >= HEARTBEAT_ERROR_AFTER:
                log.info("%s heartbeat recovered after %d failures",
                         kind, fails)
            fails = 0
        except Exception:  # noqa: BLE001 — the loop must outlive faults
            fails += 1
            METRICS.inc("heartbeat_failures_total", kind=kind)
            if fails == HEARTBEAT_ERROR_AFTER:
                log.error(
                    "%s heartbeat failed %d times in a row — the zero "
                    "link is likely dead (this node will be marked "
                    "dead by zero's liveness sweep if this persists)",
                    kind, fails, exc_info=True)
            else:
                log.debug("%s heartbeat failed (%d consecutive)",
                          kind, fails, exc_info=True)


def cmd_alpha(args) -> int:
    from dgraph_tpu.server.api import Alpha
    from dgraph_tpu.server.http import make_http_server, serve_background
    from dgraph_tpu.server.task import make_server

    overrides = {
        "p_dir": args.p, "http_port": args.http_port,
        "grpc_port": args.grpc_port, "log_level": args.log_level,
        "mesh_devices": args.mesh_devices,
        "encryption_key_file": args.encryption_key_file,
        "encryption_strict": args.encryption_strict or None,
        "memory_budget_mb": args.memory_budget_mb,
        "device_budget_mb": args.device_budget_mb,
        "host_cache_budget_mb": args.host_cache_budget_mb,
        "slow_query_ms": args.slow_query_ms,
        "trace_dir": args.trace_dir,
        "trace_export": args.trace_export,
        "rollup_after": args.rollup_after,
        "checkpoint_every_s": args.checkpoint_every_s,
        "maintenance_pacing_ms": args.maintenance_pacing_ms,
        "max_inflight": args.max_inflight,
        "queue_depth": args.queue_depth,
        "default_deadline_ms": args.default_deadline_ms,
        "cost_priors": args.cost_priors,
        "ts_interval_s": args.ts_interval_s,
        "ts_ring_points": args.ts_ring_points,
        "slo_spec": args.slo_spec,
        "forecast_shedding": args.forecast_shedding,
        "telemetry_push_url": args.telemetry_push_url,
        "telemetry_push_interval_s": args.telemetry_push_interval_s,
        "diag_dir": args.diag_dir,
        "stall_factor": args.stall_factor,
        "stall_floor_ms": args.stall_floor_ms,
        "rpc_retries": args.rpc_retries,
        "breaker_threshold": args.breaker_threshold,
        "breaker_cooldown_ms": args.breaker_cooldown_ms}
    if args.store:
        # grouped superflag (reference: z.SuperFlag, e.g.
        # --badger "compression=zstd; numgoroutines=8")
        from dgraph_tpu.utils.config import parse_superflag
        probe = AlphaConfig()
        for k, v in parse_superflag(args.store).items():
            if not hasattr(probe, k):
                raise SystemExit(f"unknown --store key {k!r}")
            if overrides.get(k) is None:  # dedicated flags win
                overrides[k] = v
    cfg = load_config(AlphaConfig, args.config, overrides)
    xlog.setup(cfg.log_level)
    log = xlog.get("alpha")
    if cfg.encryption_key_file:
        # at-rest encryption for every checkpoint file and WAL record
        # this process writes or reads (reference: ee encryption,
        # --encryption key-file=)
        from dgraph_tpu.store import vault
        vault.load_key_file(cfg.encryption_key_file,
                            strict=cfg.encryption_strict)
        log.info("encryption-at-rest enabled (strict=%s)",
                 cfg.encryption_strict)

    mesh = None
    if cfg.mesh_devices:
        # SPMD serving: the query engine runs its hops sharded over the
        # device mesh (reference: the sidecar seam, SURVEY §3.1). With a
        # coordinator (flag or JAX_COORDINATOR_ADDRESS env) the mesh
        # spans HOSTS: jax.distributed joins the processes over DCN and
        # jax.devices() below covers every host's chips.
        from dgraph_tpu.parallel.mesh import init_distributed, make_mesh
        if init_distributed(args.jax_coordinator):
            import jax as _jax
            log.info("multi-host runtime: process %d/%d",
                     _jax.process_index(), _jax.process_count())
        mesh = make_mesh(None if cfg.mesh_devices < 0
                         else cfg.mesh_devices)
        log.info("device mesh: %d devices", mesh.devices.size)

    # checkpoint + WAL replay boot: every commit that reached disk before
    # a crash is recovered (reference: badger open + raft WAL restore)
    alpha = Alpha.open(cfg.p_dir, device_threshold=cfg.device_threshold,
                       mesh=mesh,
                       memory_budget=(cfg.memory_budget_mb << 20)
                       if cfg.memory_budget_mb else None)
    alpha.slow_query_ms = cfg.slow_query_ms
    # unified cache governor (utils/memgov.py): arm the process-wide
    # byte budgets — every registered cache (fused programs, ELL
    # plans/kernels, device relations, tablet adapters, LazyPreds
    # residency) evicts above 90% of its kind's budget down to 70%,
    # lowest predicted recompute-value-per-byte first; governed launch
    # sites absorb allocation failures with one evict-retry, then
    # sticky-degrade the shape to the staged/host route
    if cfg.device_budget_mb or cfg.host_cache_budget_mb:
        from dgraph_tpu.utils import memgov
        memgov.GOVERNOR.set_budgets(
            device_bytes=cfg.device_budget_mb << 20,
            host_bytes=cfg.host_cache_budget_mb << 20)
        log.info("memory governor armed: device_budget_mb=%d "
                 "host_cache_budget_mb=%d (caches: %s)",
                 cfg.device_budget_mb, cfg.host_cache_budget_mb,
                 ",".join(sorted(memgov.GOVERNOR.registered_names())))
    # request lifecycle: admission control (token limit + bounded FIFO
    # queue + shedding) and the default per-request budget
    if cfg.max_inflight > 0:
        alpha.attach_admission(cfg.max_inflight, cfg.queue_depth,
                               default_deadline_ms=cfg.default_deadline_ms)
        log.info("admission control armed: max_inflight=%d "
                 "queue_depth=%d default_deadline_ms=%.0f",
                 cfg.max_inflight, cfg.queue_depth,
                 cfg.default_deadline_ms)
    elif cfg.default_deadline_ms:
        alpha.default_deadline_ms = cfg.default_deadline_ms
        log.info("default request deadline: %.0f ms",
                 cfg.default_deadline_ms)
    # cost-prior scheduling (utils/costprior.py): per-shape predicted
    # cost feeds admission shedding/hints, batch-plan ordering, and the
    # placement heartbeat; --no-cost_priors restores count/EMA behavior
    alpha.cost_priors = cfg.cost_priors
    if not cfg.cost_priors:
        log.info("cost-prior scheduling DISABLED (--no-cost_priors): "
                 "admission/planning fall back to count + lane EMA")
    if cfg.slow_query_ms:
        log.info("slow-query log armed at %d ms", cfg.slow_query_ms)
    if cfg.trace_dir:
        # device-timeline capture: spans marked device=True also write
        # jax.profiler traces (Perfetto) under this dir; POST
        # /debug/profile starts/stops on-demand captures under it too
        from dgraph_tpu.utils import tracing
        tracing.enable_device_trace(cfg.trace_dir)
        log.info("device trace capture armed: %s", cfg.trace_dir)
    pusher = None
    if cfg.telemetry_push_url:
        # live span + cost-record streaming to an external collector
        # (bounded buffer, retry-with-backoff, counted drops); unset =
        # graceful no-op — the historical shutdown/pull-only posture
        from dgraph_tpu.utils.push import TelemetryPusher
        pusher = TelemetryPusher(
            cfg.telemetry_push_url,
            interval_s=cfg.telemetry_push_interval_s).start()
        log.info("telemetry push armed: %s every %.1fs",
                 cfg.telemetry_push_url, cfg.telemetry_push_interval_s)
    if args.acl_secret_file:
        # ACL enforcement (reference: ee/acl --acl_secret_file): groot
        # bootstrap + token-gated endpoints
        from dgraph_tpu.server.acl import AclManager
        secret = open(args.acl_secret_file).read().strip()
        alpha.acl = AclManager(alpha, secret)
        alpha.acl.ensure_groot()
        log.info("ACL enforcement enabled")
    log.info("opened %s: %d nodes", cfg.p_dir, alpha.mvcc.base.n_nodes)

    grpc_server, grpc_port = make_server(
        alpha, f"{cfg.http_addr}:{cfg.grpc_port}")
    grpc_server.start()
    if args.zero:
        # cluster mode: Zero leases + membership + tablet routing
        from dgraph_tpu.cluster.groups import Groups
        from dgraph_tpu.cluster.zero import RemoteOracle, ZeroClient
        # capture the REPLAYED watermarks before swapping oracles: the
        # local oracle was bumped past every WAL-tail commit_ts/uid during
        # Alpha.open, and handing Zero anything lower would let it lease
        # duplicate timestamps/uids after a crash-restart rejoin
        replayed_ts = alpha.oracle.max_assigned
        replayed_uid = alpha.oracle.max_uid
        zero = ZeroClient(args.zero)
        alpha.oracle = RemoteOracle(zero)
        alpha.xidmap._oracle = alpha.oracle
        alpha.groups = Groups(
            zero, f"{cfg.http_addr}:{grpc_port}", group=args.group,
            max_ts=max(alpha.mvcc.base_ts, replayed_ts),
            max_uid=replayed_uid,
            breaker_threshold=cfg.breaker_threshold,
            breaker_cooldown_ms=cfg.breaker_cooldown_ms,
            rpc_retries=cfg.rpc_retries)
        log.info("joined cluster: node=%d group=%d",
                 alpha.groups.node_id, alpha.groups.gid)
        # rejoin catch-up: pull any WAL tail we missed while down, then
        # force freshness re-checks on every foreign tablet (reference:
        # restarted follower replays the leader's log + snapshot)
        if alpha.groups.other_addrs():
            alpha.resync_on_join()

        def liveness_step():
            # liveness ping + applied watermarks (reference: membership
            # heartbeat; the watermarks seed a promoted standby's lease
            # floor). Survives a zero failover via the client's
            # multi-target rotation + breaker-ordered dead marking.
            ts = max(alpha.mvcc.base_ts,
                     max((l.commit_ts for l in alpha.mvcc.layers),
                         default=0))
            zero.heartbeat(alpha.groups.node_id,
                           group=alpha.groups.gid, max_ts=ts,
                           max_uid=alpha.mvcc.max_uid_seen)

        import threading
        # feed Zero's rebalance loop (reference: tablet-size report in
        # the membership heartbeat); failures are metered + escalated
        # by run_heartbeat_loop instead of dying silently at debug
        threading.Thread(target=run_heartbeat_loop, daemon=True,
                         args=("size", 30.0,
                               alpha.report_tablet_sizes, log)).start()
        threading.Thread(target=run_heartbeat_loop, daemon=True,
                         args=("liveness", args.heartbeat,
                               liveness_step, log)).start()
        # peer-health + tablet-cost heartbeat (ISSUE 9): Zero's
        # tablet-move decisions read this node's breaker table and
        # measured per-tablet cost sums (Alpha.report_health →
        # ZeroService.ReportHealth) so moves prefer healthy,
        # under-loaded peers and never target half-open/dead ones
        threading.Thread(target=run_heartbeat_loop, daemon=True,
                         args=("health", 15.0,
                               alpha.report_health, log)).start()
    # background maintenance: rollup-when-deep + periodic checkpoint +
    # admin-triggered backup/export, paced and budget-bounded
    # (store/maintenance.py; reference: Badger's background rollups,
    # snapshot ticker, and ee backup workers run WHILE serving)
    alpha.attach_maintenance(
        cfg.p_dir, rollup_after=cfg.rollup_after,
        checkpoint_every_s=cfg.checkpoint_every_s,
        pacing_ms=cfg.maintenance_pacing_ms)
    if cfg.rollup_after or cfg.checkpoint_every_s:
        log.info("maintenance armed: rollup_after=%d "
                 "checkpoint_every_s=%.1f pacing_ms=%.1f",
                 cfg.rollup_after, cfg.checkpoint_every_s,
                 cfg.maintenance_pacing_ms)
    # flight recorder (utils/flightrec.py): always-on black box —
    # bounded event ring + the predicted-cost watchdog. A request
    # running stall_factor× past its costprior prediction, a wedged
    # queue head, a stalled maintenance job, or a wedged telemetry
    # pusher writes a self-contained diagnostic bundle to diag_dir
    # with NO operator action; SIGUSR2 and POST /debug/flightrecorder
    # dump on demand
    import dataclasses as _dc
    import os as _os

    from dgraph_tpu.utils import flightrec
    diag_dir = cfg.diag_dir or _os.path.join(cfg.p_dir, "diag")
    flightrec.arm(
        diag_dir=diag_dir, stall_factor=cfg.stall_factor,
        stall_floor_ms=cfg.stall_floor_ms, alpha=alpha, pusher=pusher,
        signals=True,
        config={f.name: getattr(cfg, f.name)
                for f in _dc.fields(cfg)})
    log.info("flight recorder armed: diag_dir=%s stall_factor=%.1f "
             "stall_floor_ms=%.0f (SIGUSR2 or POST "
             "/debug/flightrecorder dumps a bundle)", diag_dir,
             cfg.stall_factor, cfg.stall_floor_ms)
    if cfg.ts_interval_s > 0:
        # retained metrics history + SLO burn-rate engine + load
        # forecast (utils/timeseries.py, utils/slo.py): the sampler
        # daemon snapshots the registry every tick into the memgov-
        # governed ring, evaluates fast/slow-window burn rates (a
        # breach emits a flight event with an exemplar trace id; a
        # SUSTAINED fast burn convicts via the watchdog as kind=slo),
        # and feeds admission's predicted-load shedding
        from dgraph_tpu.utils import slo, timeseries
        engine = slo.SloEngine(slo.parse_spec(cfg.slo_spec))
        timeseries.arm(interval_s=cfg.ts_interval_s,
                       ring_points=cfg.ts_ring_points,
                       slo_engine=engine,
                       forecast=cfg.forecast_shedding)
        log.info("time-series sampler armed: interval_s=%.1f "
                 "ring_points=%d slos=%s forecast_shedding=%s "
                 "(/debug/timeseries, /debug/slo)",
                 cfg.ts_interval_s, cfg.ts_ring_points,
                 ",".join(sorted(engine.targets)),
                 cfg.forecast_shedding)
    http_server = make_http_server(alpha, cfg.http_addr, cfg.http_port)
    serve_background(http_server)
    log.info("alpha up: grpc=%d http=%d", grpc_port,
             http_server.server_address[1])
    try:
        grpc_server.wait_for_termination()
    except KeyboardInterrupt:
        # drain the in-flight maintenance job (a half-written triggered
        # backup must finish), then the final checkpoint
        log.info("shutting down; draining maintenance + checkpointing "
                 "to %s", cfg.p_dir)
        alpha.shutdown(cfg.p_dir)
        if pusher is not None:
            pusher.stop(flush=True)  # best-effort final batch
        if cfg.trace_export:
            # span registry → OTLP/JSON for an external collector
            from dgraph_tpu.utils import tracing
            n = tracing.export_otlp(cfg.trace_export)
            log.info("exported %d spans as OTLP/JSON to %s", n,
                     cfg.trace_export)
    return 0


def cmd_zero(args) -> int:
    # Standalone cluster manager (reference: dgraph zero): ts/uid leases,
    # commit arbitration, membership, tablet assignment/rebalance — the
    # full pb.Zero surface (cluster/zero.py). With --w the state machine
    # journals to disk and a restart preserves tablets and watermarks.
    import threading

    from dgraph_tpu.cluster.zero import (ZeroState, make_zero_server,
                                         rebalance_once)

    xlog.setup(args.log_level)
    log = xlog.get("zero")
    state = ZeroState(
        replicas=args.replicas,
        journal_path=(f"{args.w}/zero.journal" if args.w else None),
        txn_timeout_s=args.txn_timeout,
        liveness_s=args.liveness,
        standby=bool(args.peer))
    server, port, _state = make_zero_server(state,
                                            f"127.0.0.1:{args.port}")
    server.start()
    log.info("zero up: grpc=%d replicas=%d journal=%s role=%s", port,
             args.replicas, args.w or "off",
             "standby" if args.peer else "primary")
    if args.peer:
        # standby: tail the primary's state machine; promote when it
        # stays dark (reference: group-0 follower + failover)
        from dgraph_tpu.cluster.zero import run_standby

        # elections are SAFE BY DEFAULT: with standby peers configured,
        # promotion needs a majority of the electorate reachable
        # (require_quorum=None → auto-on in run_standby); availability
        # mode is an explicit opt-out that run_standby logs loudly
        require_quorum = None
        if args.election_availability:
            require_quorum = False
        elif args.election_quorum:
            require_quorum = True

        def standby_loop():
            peers = [a for a in (args.standby_peers or "").split(",")
                     if a]
            if run_standby(state, args.peer,
                           promote_after_s=args.promote_after,
                           peers=peers, my_addr=f"127.0.0.1:{args.port}",
                           require_quorum=require_quorum):
                log.warning("primary %s unreachable %.1fs — PROMOTED; "
                            "now serving leases", args.peer,
                            args.promote_after)

        threading.Thread(target=standby_loop, daemon=True).start()

    def maintenance():
        import time
        # graftlint: allow(retry-deadline): daemon scheduler — the sleep
        # is the tick cadence, not a backoff; no request budget exists
        while True:
            time.sleep(max(args.txn_timeout / 2, 1.0)
                       if args.txn_timeout else 10.0)
            try:
                n = state.expire_stale_txns()
                if n:
                    log.info("expired %d abandoned txns", n)
                if args.rebalance and rebalance_once(state):
                    log.info("rebalanced one tablet")
            except Exception:  # noqa: BLE001 — the loop must outlive bugs
                log.exception("zero maintenance sweep failed")

    t = threading.Thread(target=maintenance, daemon=True)
    t.start()
    server.wait_for_termination()
    return 0


def cmd_bulk(args) -> int:
    from dgraph_tpu.loader.bulk import run_bulk
    xlog.setup(args.log_level)
    rdf = open(args.files).read()
    schema = open(args.schema).read() if args.schema else ""
    st = run_bulk(rdf, args.out, schema_text=schema,
                  n_mappers=args.mappers)
    print(json.dumps({"nquads": st.nquads, "nodes": st.nodes,
                      "edges": st.edges, "elapsed_s": round(st.elapsed_s, 3)}))
    return 0


def cmd_live(args) -> int:
    from dgraph_tpu.loader.live import run_live
    from dgraph_tpu.server.api import Alpha
    from dgraph_tpu.store import checkpoint
    xlog.setup(args.log_level)
    import os
    base = None
    if os.path.exists(os.path.join(args.p, "manifest.json")):
        base, _ = checkpoint.load(args.p)
    alpha = Alpha(base=base)
    if args.schema:
        alpha.alter(open(args.schema).read())
    st = run_live(alpha, open(args.files).read(),
                  batch_size=args.batch, concurrency=args.conc)
    checkpoint.save(alpha.mvcc.rollup(), args.p, base_ts=alpha.mvcc.base_ts)
    print(json.dumps({"nquads": st.nquads, "txns": st.txns,
                      "aborts": st.aborts,
                      "elapsed_s": round(st.elapsed_s, 3)}))
    return 0


def cmd_backup(args) -> int:
    """Binary backup: full or incremental-since-last (reference:
    ee/backup; SURVEY §2.5). --memory_budget_mb opens the source
    out-of-core so a store larger than RAM backs up streamed.
    `dgraph_tpu backup verify --dest D` walks the whole chain offline
    (manifests, per-file digests, delta record counts, contiguity) and
    exits non-zero on any integrity error."""
    xlog.setup(args.log_level)
    if args.verb == "verify":
        from dgraph_tpu.server.backup import verify_chain
        report = verify_chain(args.dest)
        print(json.dumps(report, indent=1))
        return 0 if report["ok"] else 1
    from dgraph_tpu.server.backup import backup
    m = backup(args.p, args.dest, force_full=args.full,
               memory_budget=(args.memory_budget_mb << 20)
               if args.memory_budget_mb else None)
    print(json.dumps(m))
    return 0


def cmd_restore(args) -> int:
    """Rebuild a posting dir from a backup series (reference: ee
    restore). Crash-safe + resumable: a kill leaves the previous store
    serveable, a re-run resumes from the last verified tablet;
    --memory_budget_mb streams the fold so a chain bigger than RAM
    restores under budget."""
    from dgraph_tpu.server.backup import restore
    xlog.setup(args.log_level)
    ts = restore(args.dest, args.p,
                 memory_budget=(args.memory_budget_mb << 20)
                 if args.memory_budget_mb else None)
    print(json.dumps({"restored_max_ts": ts, "p_dir": args.p}))
    return 0


def cmd_export(args) -> int:
    from dgraph_tpu.server.export import export_json, export_rdf
    from dgraph_tpu.store import checkpoint
    if args.memory_budget_mb:
        # stream the export: tablets fault in one at a time and release
        # (store/stream.py) — a snapshot larger than RAM exports fine
        from dgraph_tpu.store.outofcore import open_out_of_core
        store, _ = open_out_of_core(args.p, args.memory_budget_mb << 20)
    else:
        store, _ = checkpoint.load(args.p)
    with open(args.out, "w") as f:
        n = (export_json if args.format == "json" else export_rdf)(store, f)
    print(json.dumps({"exported": n, "format": args.format}))
    return 0


def _safe_name(addr: str) -> str:
    return "".join(c if c.isalnum() else "-" for c in addr)


def _diagnose_fleet(args) -> int:
    """`dgraph_tpu diagnose --fleet`: one directory of diagnostics for
    the WHOLE cluster — the addressed server's full bundle (the PR-13
    verb), the fleet snapshot, and every known peer's flight-recorder
    snapshot pulled through the server's /debug/fleet/flight proxy
    (the DebugFlight worker RPC), each file named by node."""
    import os
    import urllib.request
    base = f"http://{args.addr}"
    out_dir = args.out or ("fleet-" + _safe_name(args.addr))
    os.makedirs(out_dir, exist_ok=True)
    req = urllib.request.Request(
        base + "/debug/flightrecorder",
        data=json.dumps({"action": "dump"}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    if args.token:
        req.add_header("X-Dgraph-AccessToken", args.token)
    # graftlint: allow(direct-io): operator CLI pulling diagnostics
    # over a server's HTTP surface — not a cluster RPC; no breaker/
    # retry/budget layer applies to a one-shot diagnostic pull
    with urllib.request.urlopen(req, timeout=args.timeout) as r:
        bundle = json.loads(r.read())["data"]["bundle"]
    with open(os.path.join(out_dir, "local.json"), "w") as f:
        json.dump(bundle, f)
    # graftlint: allow(direct-io): same one-shot operator pull
    with urllib.request.urlopen(base + "/debug/fleet",
                                timeout=args.timeout) as r:
        fleet_doc = json.loads(r.read())
    with open(os.path.join(out_dir, "fleet.json"), "w") as f:
        json.dump(fleet_doc, f)
    nodes = sorted(fleet_doc.get("nodes", {}))
    written, errors = ["local.json", "fleet.json"], dict(
        fleet_doc.get("errors", {}))
    for node in nodes:
        if node == fleet_doc.get("self"):
            continue  # the local bundle already covers this node
        try:
            # graftlint: allow(direct-io): same one-shot operator pull
            with urllib.request.urlopen(
                    base + "/debug/fleet/flight?peer=" + node,
                    timeout=args.timeout) as r:
                doc = json.loads(r.read())
            name = _safe_name(node) + ".json"
            with open(os.path.join(out_dir, name), "w") as f:
                json.dump(doc, f)
            written.append(name)
        except Exception as e:  # noqa: BLE001 — a dark peer degrades the pull
            errors[node] = f"{type(e).__name__}: {e}"
    print(json.dumps({"dir": out_dir, "nodes": nodes,
                      "written": written, "errors": errors}))
    return 0 if not errors else 1


def cmd_diagnose(args) -> int:
    """Pull a one-shot diagnostic bundle from a LIVE server: POST
    /debug/flightrecorder {"action": "dump"} makes the server build
    (and, when armed with a diag dir, also persist) the full bundle —
    all-thread stacks, the flight ring, every debug surface, metrics,
    config — and return it inline; this verb writes it to --out.
    `--fleet` widens the pull to every known cluster node (one
    directory, one file per node)."""
    import urllib.request
    xlog.setup(args.log_level)
    if args.fleet:
        return _diagnose_fleet(args)
    url = f"http://{args.addr}/debug/flightrecorder"
    req = urllib.request.Request(
        url, data=json.dumps({"action": "dump"}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    if args.token:
        req.add_header("X-Dgraph-AccessToken", args.token)
    # graftlint: allow(direct-io): operator CLI pulling a debug bundle
    # over a server's HTTP surface — not a cluster RPC; no breaker/
    # retry/budget layer applies to a one-shot diagnostic pull
    with urllib.request.urlopen(req, timeout=args.timeout) as r:
        doc = json.loads(r.read())
    bundle = doc["data"]["bundle"]
    out = args.out or ("flight-"
                       + "".join(c if c.isalnum() else "-"
                                 for c in args.addr) + ".json")
    with open(out, "w") as f:
        json.dump(bundle, f)
    print(json.dumps({
        "path": out,
        "server_path": doc["data"].get("path"),
        "trigger": bundle.get("trigger"),
        "inflight": len(bundle.get("inflight", [])),
        "surfaces": sorted(bundle.get("surfaces", {}))}))
    return 0


def cmd_fleet(args) -> int:
    """One cluster-wide observability snapshot from a live server:
    GET /debug/fleet fans out over every known node (breaker-aware,
    budget-bounded, partial on dark peers), merges the cost digests
    exactly, and instance-labels the metrics. Prints a summary;
    --out writes the full document."""
    import urllib.request
    xlog.setup(args.log_level)
    url = f"http://{args.addr}/debug/fleet"
    if args.budget_ms:
        url += f"?budget_ms={args.budget_ms:g}"
    # graftlint: allow(direct-io): operator CLI pulling a debug
    # snapshot over a server's HTTP surface — not a cluster RPC; no
    # breaker/retry/budget layer applies to a one-shot pull
    with urllib.request.urlopen(url, timeout=args.timeout) as r:
        doc = json.loads(r.read())
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f)
    nodes = doc.get("nodes", {})
    print(json.dumps({
        "self": doc.get("self"),
        "nodes": {a: {"group": n.get("group"),
                      "spans": n.get("spans"),
                      "watchdog_armed":
                          n.get("watchdog", {}).get("armed", False),
                      "gates": n.get("gates")}
                  for a, n in sorted(nodes.items())},
        "errors": doc.get("errors", {}),
        "cost_records_total":
            doc.get("costs", {}).get("records_total"),
        "out": args.out}, indent=1))
    return 0


def cmd_debug(args) -> int:
    """Snapshot inspector (reference: dgraph debug p-dir dump)."""
    from dgraph_tpu.store import checkpoint
    store, base_ts = checkpoint.load(args.p)
    info = {
        "base_ts": base_ts,
        "nodes": store.n_nodes,
        "predicates": {
            p: {"edges": pd.fwd.nnz if pd.fwd else 0,
                "reverse": pd.rev is not None,
                "value_rows": {lang or ".": len(col.subj)
                               for lang, col in pd.vals.items()},
                "indexes": sorted(pd.index)}
            for p, pd in sorted(store.preds.items())},
        "schema": store.schema.to_text(),
    }
    print(json.dumps(info, indent=1))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dgraph_tpu",
        description="TPU-native distributed graph database")
    ap.add_argument("--version", action="version",
                    version=f"dgraph_tpu {__version__}")
    sub = ap.add_subparsers(dest="cmd", required=True)

    # at-rest encryption flags, shared by every subcommand that touches
    # a posting dir, WAL, or backup series (argparse parent parser)
    enc = argparse.ArgumentParser(add_help=False)
    enc.add_argument("--encryption_key_file", default=None,
                     help="AES key file (16/24/32 bytes) → encrypt "
                          "checkpoints, WAL, and backups at rest")
    enc.add_argument("--encryption_strict", action="store_true",
                     help="reject plaintext at-rest files (post-"
                          "migration posture: unauthenticated data "
                          "cannot be read)")

    p = sub.add_parser("alpha", help="run the data server", parents=[enc])
    p.add_argument("--p", default=None,
                   help="posting snapshot dir (default: p)")
    p.add_argument("--config", default=None)
    p.add_argument("--http_port", type=int, default=None)
    p.add_argument("--grpc_port", type=int, default=None)
    p.add_argument("--store", default=None,
                   help="grouped engine knobs, 'k=v; k=v' (superflag): "
                        "device_threshold, rollup_every, mesh_devices, …")
    p.add_argument("--mesh-devices", type=int, default=None,
                   dest="mesh_devices",
                   help="SPMD engine over N devices (-1 = all, 0 = off)")
    p.add_argument("--acl_secret_file", default=None,
                   help="enable ACL; file holds the token-signing secret")
    p.add_argument("--jax-coordinator", default=None,
                   dest="jax_coordinator",
                   help="host:port of the jax.distributed coordinator "
                        "(multi-host mesh over DCN); env trio "
                        "JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/"
                        "JAX_PROCESS_ID also works")
    p.add_argument("--zero", default=None,
                   help="zero address(es) → join a cluster; a comma-"
                        "separated list fails over (primary,standby)")
    p.add_argument("--heartbeat", type=float, default=3.0,
                   help="seconds between zero liveness heartbeats")
    p.add_argument("--group", type=int, default=0,
                   help="raft-group analog to join (0 = zero picks)")
    p.add_argument("--memory_budget_mb", type=int, default=None,
                   help="out-of-core mode: fault predicate tablets from "
                        "the checkpoint on demand, LRU-evict above this "
                        "many MB resident (0 = fully resident)")
    p.add_argument("--device_budget_mb", type=int, default=None,
                   help="memory governor: HBM cache budget in MB — "
                        "device relations, shard stacks, and compiled "
                        "kernels evict above 90%% of it down to 70%%, "
                        "lowest recompute-value/byte first; governed "
                        "launches absorb allocation failures with one "
                        "evict-retry then sticky-degrade the shape "
                        "(0 = unguarded)")
    p.add_argument("--host_cache_budget_mb", type=int, default=None,
                   help="memory governor: host-RAM cache budget in MB "
                        "(fused programs, ELL plans, tablet adapters, "
                        "out-of-core residency); same watermark/"
                        "eviction policy as --device_budget_mb "
                        "(0 = unguarded)")
    p.add_argument("--rollup_after", type=int, default=None,
                   help="background-fold when this many delta layers "
                        "are pending (0 = off); out-of-core stores "
                        "stream the fold tablet-at-a-time")
    p.add_argument("--checkpoint_every_s", type=float, default=None,
                   help="periodic background checkpoint + WAL truncate "
                        "every this many seconds (0 = off)")
    p.add_argument("--maintenance_pacing_ms", type=float, default=None,
                   help="sleep between tablets of a maintenance job so "
                        "serving keeps the disk/CPU (0 = no pacing)")
    p.add_argument("--slow_query_ms", type=int, default=None,
                   help="log queries slower than this many ms with "
                        "their trace id (0 = off); spans stay "
                        "retrievable at /debug/traces?trace_id=")
    p.add_argument("--trace_dir", default=None,
                   help="arm jax.profiler device-trace capture "
                        "(Perfetto) for device-fenced spans")
    p.add_argument("--trace_export", default=None,
                   help="on shutdown, write the span registry as "
                        "OTLP/JSON to this path (collector-ready)")
    p.add_argument("--telemetry_push_url", default=None,
                   help="stream spans (OTLP /v1/traces) + query cost "
                        "records (/v1/costs) to this collector base "
                        "URL while serving; unset = export stays "
                        "shutdown/pull-shaped")
    p.add_argument("--telemetry_push_interval_s", type=float,
                   default=None,
                   help="flush cadence of the live telemetry pusher "
                        "(bounded buffer; drops are counted in "
                        "telemetry_dropped_total, never block serving)")
    p.add_argument("--diag_dir", default=None,
                   help="flight-recorder bundle dir (default: "
                        "<p_dir>/diag); the watchdog, SIGUSR2, and "
                        "POST /debug/flightrecorder write one-shot "
                        "diagnostic bundles here")
    p.add_argument("--stall_factor", type=float, default=None,
                   help="watchdog convicts an unbounded request at "
                        "this multiple of its costprior-predicted "
                        "cost (fallback: lane EMA, then "
                        "--stall_floor_ms); deadline-carrying "
                        "requests are judged against their budget")
    p.add_argument("--stall_floor_ms", type=float, default=None,
                   help="prediction fallback AND the floor a stall "
                        "conviction threshold never drops below")
    p.add_argument("--max_inflight", type=int, default=None,
                   help="admission control: concurrent requests per "
                        "lane (read/mutate); 0 = unbounded (off)")
    p.add_argument("--queue_depth", type=int, default=None,
                   help="bounded FIFO wait queue per lane; a full "
                        "queue sheds with retryable 429/ServerOverloaded")
    p.add_argument("--default_deadline_ms", type=float, default=None,
                   help="budget for requests that carry no ?timeout=/"
                        "X-Deadline-Ms of their own (0 = unbounded)")
    p.add_argument("--cost_priors", default=None,
                   action=argparse.BooleanOptionalAction,
                   help="per-shape cost priors drive admission "
                        "shedding/Retry-After, batch-plan ordering, "
                        "and the placement heartbeat (default on; "
                        "--no-cost_priors restores count/EMA-only "
                        "scheduling)")
    p.add_argument("--ts_interval_s", type=float, default=None,
                   help="metrics-history sampler cadence in seconds: "
                        "each tick snapshots the registry into the "
                        "retained ring (counters as rates, histograms "
                        "as windowed p50/p90/p99) and evaluates SLO "
                        "burn rates (0 = sampler off)")
    p.add_argument("--ts_ring_points", type=int, default=None,
                   help="retained-history ring capacity in points "
                        "(default 3600 ≈ 1h at 1s); the ring is "
                        "memgov-governed — memory pressure surrenders "
                        "the oldest history first")
    p.add_argument("--slo_spec", default=None,
                   help="SLO target overrides, 'name=value; ...' "
                        "superflag over utils/slo.SLO_SPECS (e.g. "
                        "'read_latency_p99_us=50000; "
                        "error_rate=0.001'); unnamed objectives keep "
                        "their defaults")
    p.add_argument("--forecast_shedding", default=None,
                   action=argparse.BooleanOptionalAction,
                   help="Holt-trend load forecast (arrival rate × "
                        "predicted cost) sheds admissions BEFORE the "
                        "queue fills when predicted demand exceeds "
                        "capacity (default on; --no-forecast_shedding "
                        "keeps admission purely reactive, "
                        "bit-identical to the pre-forecast path)")
    p.add_argument("--rpc_retries", type=int, default=None,
                   help="re-attempts per retryable cluster RPC "
                        "(UNAVAILABLE/connect failures only; backoff "
                        "jittered + capped by the request budget)")
    p.add_argument("--breaker_threshold", type=int, default=None,
                   help="consecutive transport failures that open a "
                        "peer's circuit breaker (then calls fail fast "
                        "until a half-open probe succeeds)")
    p.add_argument("--breaker_cooldown_ms", type=float, default=None,
                   help="open-breaker cool-down before the single "
                        "half-open probe (jittered; doubles per "
                        "re-open, capped)")
    p.add_argument("--log_level", default="info")
    p.set_defaults(fn=cmd_alpha)

    p = sub.add_parser("zero", help="run the cluster manager service", parents=[enc])
    p.add_argument("--port", type=int, default=5080)
    p.add_argument("--replicas", type=int, default=1,
                   help="replicas per group (elasticity knob)")
    p.add_argument("--w", default=None,
                   help="journal dir (state survives restart)")
    p.add_argument("--txn_timeout", type=float, default=300.0,
                   help="abort pending txns older than this — the max "
                        "transaction lifetime (0 = never)")
    p.add_argument("--rebalance", action="store_true",
                   help="enable the size-based tablet rebalance loop")
    p.add_argument("--peer", default=None,
                   help="primary zero address → run as a STANDBY that "
                        "tails its journal and promotes on failure")
    p.add_argument("--promote_after", type=float, default=5.0,
                   help="standby promotes after the primary is dark "
                        "this long")
    p.add_argument("--standby_peers", default="",
                   help="comma-separated OTHER standby addresses: on "
                        "primary failure the most caught-up standby "
                        "wins the election (highest applied journal "
                        "index), the rest re-target it")
    p.add_argument("--election_quorum", action="store_true",
                   help="require a majority of the standby electorate "
                        "reachable before promoting. This is already "
                        "the DEFAULT whenever --standby_peers is set; "
                        "the flag remains for explicitness")
    p.add_argument("--election_availability", action="store_true",
                   help="OPT OUT of quorum elections: a standby cut "
                        "off from the whole electorate still promotes "
                        "(raft's availability trade — a symmetric "
                        "partition can dual-promote; logged loudly)")
    p.add_argument("--liveness", type=float, default=10.0,
                   help="mark an alpha dead after this many seconds "
                        "without a heartbeat (0 = off)")
    p.add_argument("--log_level", default="info")
    p.set_defaults(fn=cmd_zero)

    p = sub.add_parser("bulk", help="offline bulk load → snapshot dir", parents=[enc])
    p.add_argument("--files", required=True, help="N-Quad input file")
    p.add_argument("--schema", default=None)
    p.add_argument("--out", default="p")
    p.add_argument("--mappers", type=int, default=4)
    p.add_argument("--log_level", default="info")
    p.set_defaults(fn=cmd_bulk)

    p = sub.add_parser("live", help="transactional load into a snapshot", parents=[enc])
    p.add_argument("--files", required=True)
    p.add_argument("--schema", default=None)
    p.add_argument("--p", default="p")
    p.add_argument("--batch", type=int, default=1000)
    p.add_argument("--conc", type=int, default=4)
    p.add_argument("--log_level", default="info")
    p.set_defaults(fn=cmd_live)

    p = sub.add_parser("backup", help="binary backup (full/incremental)", parents=[enc])
    p.add_argument("verb", nargs="?", choices=["verify"], default=None,
                   help="'verify' walks the chain at --dest offline: "
                        "manifests, per-file digests, delta record "
                        "counts, contiguity; exit 1 on any error")
    p.add_argument("--p", default="p", help="posting dir to back up")
    p.add_argument("--dest", required=True, help="backup series dir")
    p.add_argument("--full", action="store_true",
                   help="force a full backup even if the chain extends")
    p.add_argument("--memory_budget_mb", type=int, default=0,
                   help="open the source out-of-core and stream the "
                        "full backup tablet-at-a-time under this "
                        "budget (0 = fully resident)")
    p.add_argument("--log_level", default="info")
    p.set_defaults(fn=cmd_backup)

    p = sub.add_parser("restore", help="rebuild a posting dir from backups", parents=[enc])
    p.add_argument("--dest", required=True, help="backup series dir")
    p.add_argument("--p", required=True, help="posting dir to write")
    p.add_argument("--memory_budget_mb", type=int, default=0,
                   help="stream the restore fold tablet-at-a-time "
                        "under this budget — a backup chain bigger "
                        "than RAM restores without materializing "
                        "(0 = fully resident)")
    p.add_argument("--log_level", default="info")
    p.set_defaults(fn=cmd_restore)

    p = sub.add_parser("export", help="dump a snapshot as RDF/JSON", parents=[enc])
    p.add_argument("--p", default="p")
    p.add_argument("--out", required=True)
    p.add_argument("--format", choices=("rdf", "json"), default="rdf")
    p.add_argument("--memory_budget_mb", type=int, default=0,
                   help="stream the export out-of-core under this "
                        "budget (0 = fully resident)")
    p.set_defaults(fn=cmd_export)

    p = sub.add_parser("debug", help="inspect a snapshot dir", parents=[enc])
    p.add_argument("--p", default="p")
    p.set_defaults(fn=cmd_debug)

    p = sub.add_parser("diagnose",
                       help="pull a one-shot diagnostic bundle from a "
                            "live server's flight recorder")
    p.add_argument("addr", help="host:port of the alpha's HTTP surface")
    p.add_argument("--out", default=None,
                   help="bundle output path (default: "
                        "flight-<addr>.json); with --fleet, the "
                        "output DIRECTORY (default: fleet-<addr>/)")
    p.add_argument("--fleet", action="store_true",
                   help="pull diagnostics from EVERY known cluster "
                        "node into one directory, named by node: the "
                        "addressed server's full bundle plus each "
                        "peer's flight snapshot over the DebugFlight "
                        "RPC")
    p.add_argument("--token", default=None,
                   help="ACL access token, when the server enforces "
                        "ACL (the endpoint shares the Alter bar)")
    p.add_argument("--timeout", type=float, default=30.0)
    p.add_argument("--log_level", default="info")
    p.set_defaults(fn=cmd_diagnose)

    p = sub.add_parser("fleet",
                       help="one cluster-wide observability snapshot "
                            "(GET /debug/fleet) from a live server")
    p.add_argument("addr", help="host:port of any alpha's HTTP surface")
    p.add_argument("--out", default=None,
                   help="write the full fleet document here (the "
                        "summary always prints)")
    p.add_argument("--budget_ms", type=float, default=0.0,
                   help="overall fan-out budget (0 = server default); "
                        "peers past it degrade to an errors entry")
    p.add_argument("--timeout", type=float, default=30.0)
    p.add_argument("--log_level", default="info")
    p.set_defaults(fn=cmd_fleet)

    args = ap.parse_args(argv)
    if getattr(args, "encryption_key_file", None):
        # every subcommand that touches a posting dir, WAL, or backup
        # series honors the same at-rest key (reference: the encryption
        # superflag is process-wide)
        from dgraph_tpu.store import vault
        vault.load_key_file(args.encryption_key_file,
                            strict=getattr(args, "encryption_strict",
                                           False))
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
